"""Canonical TPC-DS query texts (spec templates with standard
parameter substitutions), restated in the engine dialect.

The analog of the reference's TPC-DS benchmark query set
(testing/trino-benchto-benchmarks/.../benchmarks/trino/tpcds.yaml).
Includes the BASELINE config #4 queries Q72 (deep 11-relation join
tree over catalog_sales x inventory) and Q95 (web_sales self-join CTE
+ IN-subqueries). Date-window parameters are aligned to the
generator's 1998-2002 sales calendar.
"""

QUERIES: dict[str, str] = {}

QUERIES["q3"] = """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manufact_id = 128
  and d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, 4 desc, brand_id
limit 100
"""

QUERIES["q7"] = """
select i_item_id,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""

QUERIES["q19"] = """
select i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 8
  and d_moy = 11
  and d_year = 1998
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
  and ss_store_sk = s_store_sk
group by i_brand_id, i_brand, i_manufact_id, i_manufact
order by 5 desc, brand, brand_id, i_manufact_id, i_manufact
limit 100
"""

QUERIES["q25"] = """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) as store_sales_profit,
       sum(sr_net_loss) as store_returns_loss,
       sum(cs_net_profit) as catalog_sales_profit
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_moy = 4
  and d1.d_year = 2001
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 4 and 10
  and d2.d_year = 2001
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_moy between 4 and 10
  and d3.d_year = 2001
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""

QUERIES["q42"] = """
select dt.d_year, item.i_category_id, item.i_category,
       sum(ss_ext_sales_price)
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11
  and dt.d_year = 2000
group by dt.d_year, item.i_category_id, item.i_category
order by 4 desc, 1, 2, 3
limit 100
"""

QUERIES["q52"] = """
select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11
  and dt.d_year = 2000
group by dt.d_year, item.i_brand_id, item.i_brand
order by 1, 4 desc, 2
limit 100
"""

QUERIES["q55"] = """
select i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 28
  and d_moy = 11
  and d_year = 1999
group by i_brand_id, i_brand
order by 3 desc, brand_id
limit 100
"""

QUERIES["q62"] = """
select w_warehouse_name, sm_type, web_name,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk <= 30)
      then 1 else 0 end) as d30,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 30)
       and (ws_ship_date_sk - ws_sold_date_sk <= 60)
      then 1 else 0 end) as d60,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 60)
       and (ws_ship_date_sk - ws_sold_date_sk <= 90)
      then 1 else 0 end) as d90,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 90)
       and (ws_ship_date_sk - ws_sold_date_sk <= 120)
      then 1 else 0 end) as d120,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 120)
      then 1 else 0 end) as dmore
from web_sales, warehouse, ship_mode, web_site, date_dim
where d_month_seq between 132 and 143
  and ws_ship_date_sk = d_date_sk
  and ws_warehouse_sk = w_warehouse_sk
  and ws_ship_mode_sk = sm_ship_mode_sk
  and ws_web_site_sk = web_site_sk
group by w_warehouse_name, sm_type, web_name
order by 1, 2, 3
limit 100
"""

QUERIES["q68"] = """
select c_last_name, c_first_name, ca_city, bought_city,
       ss_ticket_number, extended_price, extended_tax, list_price
from (
    select ss_ticket_number, ss_customer_sk, ca_city bought_city,
           sum(ss_ext_sales_price) extended_price,
           sum(ss_ext_list_price) list_price,
           sum(ss_ext_tax) extended_tax
    from store_sales, date_dim, store, household_demographics,
         customer_address
    where ss_sold_date_sk = d_date_sk
      and ss_store_sk = s_store_sk
      and ss_hdemo_sk = hd_demo_sk
      and ss_addr_sk = ca_address_sk
      and d_dom between 1 and 2
      and (hd_dep_count = 4 or hd_vehicle_count = 3)
      and d_year in (1999, 2000, 2001)
      and s_city in ('Fairview', 'Midway')
    group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city
) dn, customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, ss_ticket_number
limit 100
"""

QUERIES["q72"] = """
select i_item_desc, w_warehouse_name, d1.d_week_seq,
       sum(case when p_promo_sk is null then 1 else 0 end) no_promo,
       sum(case when p_promo_sk is not null then 1 else 0 end) promo,
       count(*) total_cnt
from catalog_sales
join inventory on cs_item_sk = inv_item_sk
join warehouse on w_warehouse_sk = inv_warehouse_sk
join item on i_item_sk = cs_item_sk
join customer_demographics on cs_bill_cdemo_sk = cd_demo_sk
join household_demographics on cs_bill_hdemo_sk = hd_demo_sk
join date_dim d1 on cs_sold_date_sk = d1.d_date_sk
join date_dim d2 on inv_date_sk = d2.d_date_sk
join date_dim d3 on cs_ship_date_sk = d3.d_date_sk
left outer join promotion on cs_promo_sk = p_promo_sk
left outer join catalog_returns on cr_item_sk = cs_item_sk
  and cr_order_number = cs_order_number
where d1.d_week_seq = d2.d_week_seq
  and inv_quantity_on_hand < cs_quantity
  and d3.d_date > d1.d_date + 5
  and hd_buy_potential = '>10000'
  and d1.d_year = 1999
  and cd_marital_status = 'D'
group by i_item_desc, w_warehouse_name, d1.d_week_seq
order by 6 desc, 1, 2, 3
limit 100
"""

QUERIES["q95"] = """
with ws_wh as (
    select ws1.ws_order_number wh_order_number
    from web_sales ws1, web_sales ws2
    where ws1.ws_order_number = ws2.ws_order_number
      and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk
)
select count(distinct ws_order_number) as order_count,
       sum(ws_ext_ship_cost) as total_shipping_cost,
       sum(ws_net_profit) as total_net_profit
from web_sales, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and date '1999-04-02'
  and ws_ship_date_sk = d_date_sk
  and ws_ship_addr_sk = ca_address_sk
  and ca_state = 'IL'
  and ws_web_site_sk = web_site_sk
  and web_company_name = 'pri'
  and ws_order_number in (select wh_order_number from ws_wh)
  and ws_order_number in (
      select wr_order_number from web_returns, ws_wh
      where wr_order_number = wh_order_number
  )
"""

QUERIES["q96"] = """
select count(*)
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = t_time_sk
  and ss_hdemo_sk = hd_demo_sk
  and ss_store_sk = s_store_sk
  and t_hour = 20
  and t_minute >= 30
  and hd_dep_count = 7
  and s_store_name = 'ese'
"""

QUERIES["q98"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) as itemrevenue,
       sum(ss_ext_sales_price) * 100 / sum(sum(ss_ext_sales_price))
           over (partition by i_class) as revenueratio
from store_sales, item, date_dim
where ss_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ss_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-03-24'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, 7
limit 100
"""

QUERIES["q37"] = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, catalog_sales
where i_current_price between 10 and 150
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-02-01' and date '2000-04-01'
  and i_manufact_id in (810, 872, 215, 901)
  and inv_quantity_on_hand between 100 and 500
  and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""

QUERIES["q82"] = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, store_sales
where i_current_price between 10 and 150
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-05-25' and date '2000-07-24'
  and i_manufact_id in (990, 465, 354, 497)
  and inv_quantity_on_hand between 100 and 500
  and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""

QUERIES["q99"] = """
select w_warehouse_name, sm_type, cc_name,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk <= 30)
      then 1 else 0 end) as d30,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 30)
       and (cs_ship_date_sk - cs_sold_date_sk <= 60)
      then 1 else 0 end) as d60,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 60)
       and (cs_ship_date_sk - cs_sold_date_sk <= 90)
      then 1 else 0 end) as d90,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 90)
       and (cs_ship_date_sk - cs_sold_date_sk <= 120)
      then 1 else 0 end) as d120,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 120)
      then 1 else 0 end) as dmore
from catalog_sales, warehouse, ship_mode, call_center, date_dim
where d_month_seq between 132 and 143
  and cs_ship_date_sk = d_date_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_ship_mode_sk = sm_ship_mode_sk
  and cs_call_center_sk = cc_call_center_sk
group by w_warehouse_name, sm_type, cc_name
order by 1, 2, 3
limit 100
"""


# ---- round-4 additions: rollup family + broad coverage (restated spec
# queries, parameters aligned to the generator calendar/domains) ----
QUERIES["q12"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ws_ext_sales_price) itemrevenue,
       sum(ws_ext_sales_price) * 100 / sum(sum(ws_ext_sales_price))
           over (partition by i_class) revenueratio
from web_sales, item, date_dim
where ws_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ws_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-02-22' + interval '30' day
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""
QUERIES["q15"] = """
select ca_zip, sum(cs_sales_price)
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and (substr(ca_zip, 1, 5) in ('85669','86197','88274','83405','86475',
                                '85392','85460','80348','81792')
       or ca_state in ('CA','WA','GA')
       or cs_sales_price > 500)
  and cs_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 2001
group by ca_zip
order by ca_zip
limit 100
"""
QUERIES["q18"] = """
select i_item_id, ca_country, ca_state, ca_county,
       avg(cast(cs_quantity as double)) agg1,
       avg(cast(cs_list_price as double)) agg2,
       avg(cast(cs_coupon_amt as double)) agg3,
       avg(cast(cs_sales_price as double)) agg4,
       avg(cast(cs_net_profit as double)) agg5,
       avg(cast(c_birth_year as double)) agg6,
       avg(cast(cd1.cd_dep_count as double)) agg7
from catalog_sales, customer_demographics cd1,
     customer_demographics cd2, customer, customer_address, date_dim, item
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd1.cd_demo_sk
  and cs_bill_customer_sk = c_customer_sk
  and cd1.cd_gender = 'F'
  and cd1.cd_education_status = 'Unknown'
  and c_current_cdemo_sk = cd2.cd_demo_sk
  and c_current_addr_sk = ca_address_sk
  and c_birth_month in (1, 6, 8, 9, 12, 2)
  and d_year = 1998
  and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA')
group by rollup(i_item_id, ca_country, ca_state, ca_county)
order by ca_country, ca_state, ca_county, i_item_id
limit 100
"""
QUERIES["q20"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(cs_ext_sales_price) itemrevenue,
       sum(cs_ext_sales_price) * 100 / sum(sum(cs_ext_sales_price))
           over (partition by i_class) revenueratio
from catalog_sales, item, date_dim
where cs_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and cs_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-02-22' + interval '30' day
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""
QUERIES["q22"] = """
select i_product_name, i_brand, i_class, i_category,
       avg(inv_quantity_on_hand) qoh
from inventory, date_dim, item
where inv_date_sk = d_date_sk
  and inv_item_sk = i_item_sk
  and d_month_seq between 108 and 119
group by rollup(i_product_name, i_brand, i_class, i_category)
order by qoh, i_product_name, i_brand, i_class, i_category
limit 100
"""
QUERIES["q26"] = """
select i_item_id, avg(cs_quantity) agg1, avg(cs_list_price) agg2,
       avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk
  and cs_promo_sk = p_promo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""
QUERIES["q27"] = """
select i_item_id, s_state, grouping(s_state) g_state,
       avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk
  and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and d_year = 2002
  and s_state in ('TN', 'TX', 'NE', 'MS')
group by rollup(i_item_id, s_state)
order by i_item_id, s_state
limit 100
"""
QUERIES["q34"] = """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, store, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and (date_dim.d_dom between 1 and 3 or date_dim.d_dom between 25 and 28)
        and (household_demographics.hd_buy_potential = '>10000'
             or household_demographics.hd_buy_potential = 'Unknown')
        and household_demographics.hd_vehicle_count > 0
        and (case when household_demographics.hd_vehicle_count > 0
             then cast(household_demographics.hd_dep_count as double)
                  / household_demographics.hd_vehicle_count
             else null end) > 1.2
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_county in ('Williamson County', 'Barrow County')
      group by ss_ticket_number, ss_customer_sk) dn, customer
where ss_customer_sk = c_customer_sk
  and cnt between 2 and 20
order by c_last_name, c_first_name, c_salutation,
         c_preferred_cust_flag desc, ss_ticket_number
"""
QUERIES["q36"] = """
select sum(ss_net_profit) / sum(ss_ext_sales_price) gross_margin,
       i_category, i_class,
       grouping(i_category) + grouping(i_class) lochierarchy,
       rank() over (partition by grouping(i_category) + grouping(i_class),
                    case when grouping(i_class) = 0 then i_category end
                    order by sum(ss_net_profit) / sum(ss_ext_sales_price))
           rank_within_parent
from store_sales, date_dim d1, item, store
where d1.d_year = 2001
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and s_state in ('TN', 'TX', 'NE', 'MS')
group by rollup(i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent, i_category, i_class
limit 100
"""
QUERIES["q43"] = """
select s_store_name, s_store_id,
       sum(case when d_day_name = 'Sunday' then ss_sales_price else null end) sun_sales,
       sum(case when d_day_name = 'Monday' then ss_sales_price else null end) mon_sales,
       sum(case when d_day_name = 'Tuesday' then ss_sales_price else null end) tue_sales,
       sum(case when d_day_name = 'Wednesday' then ss_sales_price else null end) wed_sales,
       sum(case when d_day_name = 'Thursday' then ss_sales_price else null end) thu_sales,
       sum(case when d_day_name = 'Friday' then ss_sales_price else null end) fri_sales,
       sum(case when d_day_name = 'Saturday' then ss_sales_price else null end) sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk
  and s_store_sk = ss_store_sk
  and s_gmt_offset > 0
  and d_year = 2000
group by s_store_name, s_store_id
order by s_store_name, s_store_id, sun_sales, mon_sales, tue_sales,
         wed_sales, thu_sales, fri_sales, sat_sales
limit 100
"""
QUERIES["q46"] = """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       amt, profit
from (select ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics,
           customer_address
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and store_sales.ss_addr_sk = customer_address.ca_address_sk
        and (household_demographics.hd_dep_count = 4
             or household_demographics.hd_vehicle_count = 3)
        and date_dim.d_dow in (6, 0)
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_city in ('Georgetown', 'Greenville', 'Union')
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and customer.c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number
limit 100
"""
QUERIES["q53"] = """
select * from (
  select i_manufact_id, sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) over (partition by i_manufact_id)
             avg_quarterly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_month_seq between 108 and 119
    and ((i_category in ('Books', 'Children', 'Electronics')
          and i_class in ('fiction', 'kids', 'computers'))
         or (i_category in ('Women', 'Music', 'Men')
             and i_class in ('accessories', 'classical', 'pants')))
  group by i_manufact_id, d_qoy) tmp1
where case when avg_quarterly_sales > 0
      then abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
      else null end > 0.1
order by avg_quarterly_sales, sum_sales, i_manufact_id
limit 100
"""
QUERIES["q63"] = """
select * from (
  select i_manager_id, sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) over (partition by i_manager_id)
             avg_monthly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_month_seq between 108 and 119
    and ((i_category in ('Books', 'Children', 'Electronics')
          and i_class in ('fiction', 'kids', 'computers'))
         or (i_category in ('Women', 'Music', 'Men')
             and i_class in ('accessories', 'classical', 'pants')))
  group by i_manager_id, d_moy) tmp1
where case when avg_monthly_sales > 0
      then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
      else null end > 0.1
order by i_manager_id, avg_monthly_sales, sum_sales
limit 100
"""
QUERIES["q65"] = """
select s_store_name, i_item_desc, sc.revenue, i_current_price,
       i_wholesale_cost, i_brand
from store, item,
     (select ss_store_sk, avg(revenue) ave
      from (select ss_store_sk, ss_item_sk, sum(ss_sales_price) revenue
            from store_sales, date_dim
            where ss_sold_date_sk = d_date_sk
              and d_month_seq between 108 and 119
            group by ss_store_sk, ss_item_sk) sa
      group by ss_store_sk) sb,
     (select ss_store_sk, ss_item_sk, sum(ss_sales_price) revenue
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk
        and d_month_seq between 108 and 119
      group by ss_store_sk, ss_item_sk) sc
where sb.ss_store_sk = sc.ss_store_sk
  and sc.revenue <= 0.1 * sb.ave
  and s_store_sk = sc.ss_store_sk
  and i_item_sk = sc.ss_item_sk
order by s_store_name, i_item_desc, i_brand, sc.revenue
limit 100
"""
QUERIES["q73"] = """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, store, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and date_dim.d_dom between 1 and 2
        and (household_demographics.hd_buy_potential = '>10000'
             or household_demographics.hd_buy_potential = 'Unknown')
        and household_demographics.hd_vehicle_count > 0
        and (case when household_demographics.hd_vehicle_count > 0
             then cast(household_demographics.hd_dep_count as double)
                  / household_demographics.hd_vehicle_count
             else null end) > 1
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_county in ('Williamson County', 'Furnas County')
      group by ss_ticket_number, ss_customer_sk) dj, customer
where ss_customer_sk = c_customer_sk
  and cnt between 1 and 5
order by cnt desc, c_last_name, ss_ticket_number
"""
QUERIES["q79"] = """
select c_last_name, c_first_name, substr(s_city, 1, 30), ss_ticket_number,
       amt, profit
from (select ss_ticket_number, ss_customer_sk, store.s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and (household_demographics.hd_dep_count = 6
             or household_demographics.hd_vehicle_count > 2)
        and date_dim.d_dow = 1
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_number_employees between 40 and 400
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, store.s_city) ms,
     customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, substr(s_city, 1, 30), profit
limit 100
"""
QUERIES["q86"] = """
select sum(ws_net_paid) total_sum, i_category, i_class,
       grouping(i_category) + grouping(i_class) lochierarchy,
       rank() over (partition by grouping(i_category) + grouping(i_class),
                    case when grouping(i_class) = 0 then i_category end
                    order by sum(ws_net_paid) desc) rank_within_parent
from web_sales, date_dim d1, item
where d1.d_month_seq between 108 and 119
  and d1.d_date_sk = ws_sold_date_sk
  and i_item_sk = ws_item_sk
group by rollup(i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent, i_category, i_class
limit 100
"""
QUERIES["q88"] = """
select * from
 (select count(*) h8_30_to_9 from store_sales, household_demographics,
         time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 8 and time_dim.t_minute >= 30
    and ((household_demographics.hd_dep_count = 4
          and household_demographics.hd_vehicle_count <= 6)
         or (household_demographics.hd_dep_count = 2
             and household_demographics.hd_vehicle_count <= 4)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2))
    and store.s_store_name = 'ese') s1,
 (select count(*) h9_to_9_30 from store_sales, household_demographics,
         time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 9 and time_dim.t_minute < 30
    and ((household_demographics.hd_dep_count = 4
          and household_demographics.hd_vehicle_count <= 6)
         or (household_demographics.hd_dep_count = 2
             and household_demographics.hd_vehicle_count <= 4)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2))
    and store.s_store_name = 'ese') s2,
 (select count(*) h9_30_to_10 from store_sales, household_demographics,
         time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 9 and time_dim.t_minute >= 30
    and ((household_demographics.hd_dep_count = 4
          and household_demographics.hd_vehicle_count <= 6)
         or (household_demographics.hd_dep_count = 2
             and household_demographics.hd_vehicle_count <= 4)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2))
    and store.s_store_name = 'ese') s3,
 (select count(*) h10_to_10_30 from store_sales, household_demographics,
         time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 10 and time_dim.t_minute < 30
    and ((household_demographics.hd_dep_count = 4
          and household_demographics.hd_vehicle_count <= 6)
         or (household_demographics.hd_dep_count = 2
             and household_demographics.hd_vehicle_count <= 4)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2))
    and store.s_store_name = 'ese') s4
"""
QUERIES["q89"] = """
select * from (
  select i_category, i_class, i_brand, s_store_name, s_company_name,
         d_moy, sum(ss_sales_price) sum_sales,
         avg(cast(sum(ss_sales_price) as double)) over (partition by
             i_category, i_brand, s_store_name, s_company_name)
             avg_monthly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_year in (1999)
    and ((i_category in ('Books', 'Electronics', 'Sports')
          and i_class in ('computers', 'shirts', 'baseball'))
         or (i_category in ('Men', 'Jewelry', 'Women')
             and i_class in ('accessories', 'dresses', 'pants')))
  group by i_category, i_class, i_brand, s_store_name, s_company_name,
           d_moy) tmp1
where case when avg_monthly_sales <> 0
      then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
      else null end > 0.1
order by sum_sales - avg_monthly_sales, s_store_name, i_category,
         i_class, i_brand, d_moy
limit 100
"""
QUERIES["q93"] = """
select ss_customer_sk, sum(act_sales) sumsales
from (select ss_item_sk, ss_ticket_number, ss_customer_sk,
             case when sr_return_quantity is not null
                  then (ss_quantity - sr_return_quantity) * ss_sales_price
                  else ss_quantity * ss_sales_price end act_sales
      from store_sales left join store_returns
           on sr_item_sk = ss_item_sk and sr_ticket_number = ss_ticket_number,
           reason
      where sr_reason_sk = r_reason_sk
        and r_reason_desc = 'Package was damaged') t
group by ss_customer_sk
order by sumsales, ss_customer_sk
limit 100
"""
QUERIES["q97"] = """
with ssci as (
  select ss_customer_sk customer_sk, ss_item_sk item_sk
  from store_sales, date_dim
  where ss_sold_date_sk = d_date_sk
    and d_month_seq between 108 and 119
  group by ss_customer_sk, ss_item_sk),
csci as (
  select cs_bill_customer_sk customer_sk, cs_item_sk item_sk
  from catalog_sales, date_dim
  where cs_sold_date_sk = d_date_sk
    and d_month_seq between 108 and 119
  group by cs_bill_customer_sk, cs_item_sk)
select sum(case when ssci.customer_sk is not null
                 and csci.customer_sk is null then 1 else 0 end) store_only,
       sum(case when ssci.customer_sk is null
                 and csci.customer_sk is not null then 1 else 0 end) catalog_only,
       sum(case when ssci.customer_sk is not null
                 and csci.customer_sk is not null then 1 else 0 end) store_and_catalog
from ssci full outer join csci
     on ssci.customer_sk = csci.customer_sk and ssci.item_sk = csci.item_sk
limit 100
"""

#: sqlite-oracle equivalents for queries sqlite cannot run
#: directly (ROLLUP/GROUPING spelled as explicit UNION ALLs;
#: ordering adds NULLS LAST to match engine null ordering)
SQLITE_ORACLE: dict[str, str] = {}
SQLITE_ORACLE["q18"] = """
select i_item_id, ca_country, ca_state, ca_county, avg(1.0*cs_quantity),
       avg(1.0*cs_list_price), avg(1.0*cs_coupon_amt),
       avg(1.0*cs_sales_price), avg(1.0*cs_net_profit),
       avg(1.0*c_birth_year), avg(1.0*cd_dep_count)
from (select cs_quantity, cs_list_price, cs_coupon_amt, cs_sales_price,
             cs_net_profit, c_birth_year, cd1.cd_dep_count, i_item_id,
             ca_country, ca_state, ca_county
      from catalog_sales, customer_demographics cd1,
           customer_demographics cd2, customer, customer_address,
           date_dim, item
      where cs_sold_date_sk = d_date_sk
        and cs_item_sk = i_item_sk
        and cs_bill_cdemo_sk = cd1.cd_demo_sk
        and cs_bill_customer_sk = c_customer_sk
        and cd1.cd_gender = 'F'
        and cd1.cd_education_status = 'Unknown'
        and c_current_cdemo_sk = cd2.cd_demo_sk
        and c_current_addr_sk = ca_address_sk
        and c_birth_month in (1, 6, 8, 9, 12, 2)
        and d_year = 1998
        and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA'))
group by i_item_id, ca_country, ca_state, ca_county
union all
select i_item_id, ca_country, ca_state, null, avg(1.0*cs_quantity),
       avg(1.0*cs_list_price), avg(1.0*cs_coupon_amt),
       avg(1.0*cs_sales_price), avg(1.0*cs_net_profit),
       avg(1.0*c_birth_year), avg(1.0*cd_dep_count)
from (select cs_quantity, cs_list_price, cs_coupon_amt, cs_sales_price,
             cs_net_profit, c_birth_year, cd1.cd_dep_count, i_item_id,
             ca_country, ca_state
      from catalog_sales, customer_demographics cd1,
           customer_demographics cd2, customer, customer_address,
           date_dim, item
      where cs_sold_date_sk = d_date_sk
        and cs_item_sk = i_item_sk
        and cs_bill_cdemo_sk = cd1.cd_demo_sk
        and cs_bill_customer_sk = c_customer_sk
        and cd1.cd_gender = 'F'
        and cd1.cd_education_status = 'Unknown'
        and c_current_cdemo_sk = cd2.cd_demo_sk
        and c_current_addr_sk = ca_address_sk
        and c_birth_month in (1, 6, 8, 9, 12, 2)
        and d_year = 1998
        and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA'))
group by i_item_id, ca_country, ca_state
union all
select i_item_id, ca_country, null, null, avg(1.0*cs_quantity),
       avg(1.0*cs_list_price), avg(1.0*cs_coupon_amt),
       avg(1.0*cs_sales_price), avg(1.0*cs_net_profit),
       avg(1.0*c_birth_year), avg(1.0*cd_dep_count)
from (select cs_quantity, cs_list_price, cs_coupon_amt, cs_sales_price,
             cs_net_profit, c_birth_year, cd1.cd_dep_count, i_item_id,
             ca_country
      from catalog_sales, customer_demographics cd1,
           customer_demographics cd2, customer, customer_address,
           date_dim, item
      where cs_sold_date_sk = d_date_sk
        and cs_item_sk = i_item_sk
        and cs_bill_cdemo_sk = cd1.cd_demo_sk
        and cs_bill_customer_sk = c_customer_sk
        and cd1.cd_gender = 'F'
        and cd1.cd_education_status = 'Unknown'
        and c_current_cdemo_sk = cd2.cd_demo_sk
        and c_current_addr_sk = ca_address_sk
        and c_birth_month in (1, 6, 8, 9, 12, 2)
        and d_year = 1998
        and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA'))
group by i_item_id, ca_country
union all
select i_item_id, null, null, null, avg(1.0*cs_quantity),
       avg(1.0*cs_list_price), avg(1.0*cs_coupon_amt),
       avg(1.0*cs_sales_price), avg(1.0*cs_net_profit),
       avg(1.0*c_birth_year), avg(1.0*cd_dep_count)
from (select cs_quantity, cs_list_price, cs_coupon_amt, cs_sales_price,
             cs_net_profit, c_birth_year, cd1.cd_dep_count, i_item_id
      from catalog_sales, customer_demographics cd1,
           customer_demographics cd2, customer, customer_address,
           date_dim, item
      where cs_sold_date_sk = d_date_sk
        and cs_item_sk = i_item_sk
        and cs_bill_cdemo_sk = cd1.cd_demo_sk
        and cs_bill_customer_sk = c_customer_sk
        and cd1.cd_gender = 'F'
        and cd1.cd_education_status = 'Unknown'
        and c_current_cdemo_sk = cd2.cd_demo_sk
        and c_current_addr_sk = ca_address_sk
        and c_birth_month in (1, 6, 8, 9, 12, 2)
        and d_year = 1998
        and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA'))
group by i_item_id
union all
select null, null, null, null, avg(1.0*cs_quantity),
       avg(1.0*cs_list_price), avg(1.0*cs_coupon_amt),
       avg(1.0*cs_sales_price), avg(1.0*cs_net_profit),
       avg(1.0*c_birth_year), avg(1.0*cd_dep_count)
from (select cs_quantity, cs_list_price, cs_coupon_amt, cs_sales_price,
             cs_net_profit, c_birth_year, cd1.cd_dep_count
      from catalog_sales, customer_demographics cd1,
           customer_demographics cd2, customer, customer_address,
           date_dim, item
      where cs_sold_date_sk = d_date_sk
        and cs_item_sk = i_item_sk
        and cs_bill_cdemo_sk = cd1.cd_demo_sk
        and cs_bill_customer_sk = c_customer_sk
        and cd1.cd_gender = 'F'
        and cd1.cd_education_status = 'Unknown'
        and c_current_cdemo_sk = cd2.cd_demo_sk
        and c_current_addr_sk = ca_address_sk
        and c_birth_month in (1, 6, 8, 9, 12, 2)
        and d_year = 1998
        and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA'))
order by 2, 3, 4, 1
limit 100
"""
SQLITE_ORACLE["q22"] = """
select i_product_name, i_brand, i_class, i_category,
       avg(1.0*inv_quantity_on_hand) qoh
from inventory, date_dim, item
where inv_date_sk = d_date_sk and inv_item_sk = i_item_sk
  and d_month_seq between 108 and 119
group by i_product_name, i_brand, i_class, i_category
union all
select i_product_name, i_brand, i_class, null, avg(1.0*inv_quantity_on_hand)
from inventory, date_dim, item
where inv_date_sk = d_date_sk and inv_item_sk = i_item_sk
  and d_month_seq between 108 and 119
group by i_product_name, i_brand, i_class
union all
select i_product_name, i_brand, null, null, avg(1.0*inv_quantity_on_hand)
from inventory, date_dim, item
where inv_date_sk = d_date_sk and inv_item_sk = i_item_sk
  and d_month_seq between 108 and 119
group by i_product_name, i_brand
union all
select i_product_name, null, null, null, avg(1.0*inv_quantity_on_hand)
from inventory, date_dim, item
where inv_date_sk = d_date_sk and inv_item_sk = i_item_sk
  and d_month_seq between 108 and 119
group by i_product_name
union all
select null, null, null, null, avg(1.0*inv_quantity_on_hand)
from inventory, date_dim, item
where inv_date_sk = d_date_sk and inv_item_sk = i_item_sk
  and d_month_seq between 108 and 119
order by 5, 1 nulls last, 2 nulls last, 3 nulls last, 4 nulls last
limit 100
"""
SQLITE_ORACLE["q27"] = """
select i_item_id, s_state, 0, avg(1.0*ss_quantity), avg(1.0*ss_list_price),
       avg(1.0*ss_coupon_amt), avg(1.0*ss_sales_price)
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College' and d_year = 2002
  and s_state in ('TN', 'TX', 'NE', 'MS')
group by i_item_id, s_state
union all
select i_item_id, null, 1, avg(1.0*ss_quantity), avg(1.0*ss_list_price),
       avg(1.0*ss_coupon_amt), avg(1.0*ss_sales_price)
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College' and d_year = 2002
  and s_state in ('TN', 'TX', 'NE', 'MS')
group by i_item_id
union all
select null, null, 1, avg(1.0*ss_quantity), avg(1.0*ss_list_price),
       avg(1.0*ss_coupon_amt), avg(1.0*ss_sales_price)
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College' and d_year = 2002
  and s_state in ('TN', 'TX', 'NE', 'MS')
order by 1 nulls last, 2 nulls last
limit 100
"""
SQLITE_ORACLE["q36"] = """
select gross_margin, i_category, i_class, lochierarchy,
       rank() over (partition by lochierarchy,
                    case when lochierarchy = 0 then i_category end
                    order by gross_margin) rank_within_parent
from (
  select 1.0*sum(ss_net_profit) / sum(ss_ext_sales_price) gross_margin,
         i_category, i_class, 0 lochierarchy
  from store_sales, date_dim d1, item, store
  where d1.d_year = 2001 and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
    and s_state in ('TN', 'TX', 'NE', 'MS')
  group by i_category, i_class
  union all
  select 1.0*sum(ss_net_profit) / sum(ss_ext_sales_price), i_category,
         null, 1
  from store_sales, date_dim d1, item, store
  where d1.d_year = 2001 and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
    and s_state in ('TN', 'TX', 'NE', 'MS')
  group by i_category
  union all
  select 1.0*sum(ss_net_profit) / sum(ss_ext_sales_price), null, null, 2
  from store_sales, date_dim d1, item, store
  where d1.d_year = 2001 and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
    and s_state in ('TN', 'TX', 'NE', 'MS'))
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent, i_category, i_class
limit 100
"""
SQLITE_ORACLE["q86"] = """
select total_sum, i_category, i_class, lochierarchy,
       rank() over (partition by lochierarchy,
                    case when lochierarchy = 0 then i_category end
                    order by total_sum desc) rank_within_parent
from (
  select sum(ws_net_paid) total_sum, i_category, i_class, 0 lochierarchy
  from web_sales, date_dim d1, item
  where d1.d_month_seq between 108 and 119
    and d1.d_date_sk = ws_sold_date_sk and i_item_sk = ws_item_sk
  group by i_category, i_class
  union all
  select sum(ws_net_paid), i_category, null, 1
  from web_sales, date_dim d1, item
  where d1.d_month_seq between 108 and 119
    and d1.d_date_sk = ws_sold_date_sk and i_item_sk = ws_item_sk
  group by i_category
  union all
  select sum(ws_net_paid), null, null, 2
  from web_sales, date_dim d1, item
  where d1.d_month_seq between 108 and 119
    and d1.d_date_sk = ws_sold_date_sk and i_item_sk = ws_item_sk)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent, i_category, i_class
limit 100
"""

QUERIES["q13"] = """
select avg(ss_quantity), avg(ss_ext_sales_price),
       avg(ss_ext_wholesale_cost), sum(ss_ext_wholesale_cost)
from store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk
  and d_year = 2001
  and ((ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'M'
        and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 100.00 and 150.00
        and hd_dep_count = 3)
       or (ss_hdemo_sk = hd_demo_sk
           and cd_demo_sk = ss_cdemo_sk
           and cd_marital_status = 'S'
           and cd_education_status = 'College'
           and ss_sales_price between 50.00 and 100.00
           and hd_dep_count = 1)
       or (ss_hdemo_sk = hd_demo_sk
           and cd_demo_sk = ss_cdemo_sk
           and cd_marital_status = 'W'
           and cd_education_status = '2 yr Degree'
           and ss_sales_price between 150.00 and 200.00
           and hd_dep_count = 1))
  and ((ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('TX', 'OH', 'KS')
        and ss_net_profit between 100 and 200)
       or (ss_addr_sk = ca_address_sk
           and ca_country = 'United States'
           and ca_state in ('OR', 'NE', 'KY')
           and ss_net_profit between 150 and 300)
       or (ss_addr_sk = ca_address_sk
           and ca_country = 'United States'
           and ca_state in ('VA', 'TN', 'MS')
           and ss_net_profit between 50 and 250))
"""

QUERIES["q48"] = """
select sum(ss_quantity)
from store_sales, store, customer_demographics, customer_address,
     date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk
  and d_year = 2000
  and ((cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'M'
        and cd_education_status = '4 yr Degree'
        and ss_sales_price between 100.00 and 150.00)
       or (cd_demo_sk = ss_cdemo_sk
           and cd_marital_status = 'D'
           and cd_education_status = '2 yr Degree'
           and ss_sales_price between 50.00 and 100.00)
       or (cd_demo_sk = ss_cdemo_sk
           and cd_marital_status = 'S'
           and cd_education_status = 'College'
           and ss_sales_price between 150.00 and 200.00))
  and ((ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('CO', 'OH', 'TX')
        and ss_net_profit between 0 and 2000)
       or (ss_addr_sk = ca_address_sk
           and ca_country = 'United States'
           and ca_state in ('OR', 'MN', 'KY')
           and ss_net_profit between 150 and 3000)
       or (ss_addr_sk = ca_address_sk
           and ca_country = 'United States'
           and ca_state in ('VA', 'CA', 'MS')
           and ss_net_profit between 50 and 25000))
"""

# q13/q48: sqlite cannot plan the spec's OR-embedded join conditions
# (it cross-joins and never finishes even at tiny); the oracle text is
# the factored-equivalent form — the same rewrite the engine's
# optimizer applies (ExtractCommonPredicates analog)
SQLITE_ORACLE["q13"] = """
select avg(ss_quantity), avg(ss_ext_sales_price),
       avg(ss_ext_wholesale_cost), sum(ss_ext_wholesale_cost)
from store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk
  and d_year = 2001
  and ss_hdemo_sk = hd_demo_sk
  and cd_demo_sk = ss_cdemo_sk
  and ss_addr_sk = ca_address_sk
  and ca_country = 'United States'
  and ((cd_marital_status = 'M'
        and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 100.00 and 150.00
        and hd_dep_count = 3)
       or (cd_marital_status = 'S'
           and cd_education_status = 'College'
           and ss_sales_price between 50.00 and 100.00
           and hd_dep_count = 1)
       or (cd_marital_status = 'W'
           and cd_education_status = '2 yr Degree'
           and ss_sales_price between 150.00 and 200.00
           and hd_dep_count = 1))
  and ((ca_state in ('TX', 'OH', 'KS')
        and ss_net_profit between 100 and 200)
       or (ca_state in ('OR', 'NE', 'KY')
           and ss_net_profit between 150 and 300)
       or (ca_state in ('VA', 'TN', 'MS')
           and ss_net_profit between 50 and 250))
"""

SQLITE_ORACLE["q48"] = """
select sum(ss_quantity)
from store_sales, store, customer_demographics, customer_address,
     date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk
  and d_year = 2000
  and cd_demo_sk = ss_cdemo_sk
  and ss_addr_sk = ca_address_sk
  and ca_country = 'United States'
  and ((cd_marital_status = 'M'
        and cd_education_status = '4 yr Degree'
        and ss_sales_price between 100.00 and 150.00)
       or (cd_marital_status = 'D'
           and cd_education_status = '2 yr Degree'
           and ss_sales_price between 50.00 and 100.00)
       or (cd_marital_status = 'S'
           and cd_education_status = 'College'
           and ss_sales_price between 150.00 and 200.00))
  and ((ca_state in ('CO', 'OH', 'TX')
        and ss_net_profit between 0 and 2000)
       or (ca_state in ('OR', 'MN', 'KY')
           and ss_net_profit between 150 and 3000)
       or (ca_state in ('VA', 'CA', 'MS')
           and ss_net_profit between 50 and 25000))
"""


def _rollup_union(keys, aggs, body):
    """sqlite oracle helper: spell GROUP BY ROLLUP(keys) as the union
    of its grouping sets (sqlite has no ROLLUP)."""
    branches = []
    for i in range(len(keys), -1, -1):
        cols = keys[:i] + ["null"] * (len(keys) - i)
        group = f"group by {', '.join(keys[:i])}" if i else ""
        branches.append(
            f"select {', '.join(cols)}, {aggs} {body} {group}"
        )
    return " union all ".join(branches)


QUERIES["q67"] = """
select * from (
  select i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, s_store_id, sumsales,
         rank() over (partition by i_category
                      order by sumsales desc) rk
  from (select i_category, i_class, i_brand, i_product_name, d_year,
               d_qoy, d_moy, s_store_id,
               sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales
        from store_sales, date_dim, store, item
        where ss_sold_date_sk = d_date_sk
          and ss_item_sk = i_item_sk
          and ss_store_sk = s_store_sk
          and d_month_seq between 108 and 119
        group by rollup(i_category, i_class, i_brand, i_product_name,
                        d_year, d_qoy, d_moy, s_store_id)) dw1) dw2
where rk <= 100
order by i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, s_store_id, sumsales, rk
limit 100
"""

SQLITE_ORACLE["q67"] = (
    "select * from (select i_category, i_class, i_brand, "
    "i_product_name, d_year, d_qoy, d_moy, s_store_id, sumsales, "
    "rank() over (partition by i_category order by sumsales desc) rk "
    "from ("
    + _rollup_union(
        ["i_category", "i_class", "i_brand", "i_product_name",
         "d_year", "d_qoy", "d_moy", "s_store_id"],
        "sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales",
        "from store_sales, date_dim, store, item "
        "where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk "
        "and ss_store_sk = s_store_sk "
        "and d_month_seq between 108 and 119",
    )
    + ") dw1) dw2 where rk <= 100 "
    "order by i_category nulls last, i_class nulls last, "
    "i_brand nulls last, i_product_name nulls last, "
    "d_year nulls last, d_qoy nulls last, d_moy nulls last, "
    "s_store_id nulls last, sumsales, rk limit 100"
)

_Q80_CHANNELS = """
   select 'store channel' channel, 'store' || store_id id, sales,
          returns, profit
   from (select s_store_id store_id, sum(ss_ext_sales_price) sales,
                sum(coalesce(sr_return_amt, 0)) returns,
                sum(ss_net_profit - coalesce(sr_net_loss, 0)) profit
         from store_sales left join store_returns
              on ss_item_sk = sr_item_sk
              and ss_ticket_number = sr_ticket_number,
              date_dim, store, item, promotion
         where ss_sold_date_sk = d_date_sk
           and d_date between date '2000-08-23'
               and date '2000-08-23' + interval '30' day
           and ss_store_sk = s_store_sk
           and ss_item_sk = i_item_sk
           and i_current_price > 50
           and ss_promo_sk = p_promo_sk
           and p_channel_tv = 'N'
         group by s_store_id) ssr
   union all
   select 'catalog channel', 'catalog_page' || catalog_page_id, sales,
          returns, profit
   from (select cp_catalog_page_id catalog_page_id,
                sum(cs_ext_sales_price) sales,
                sum(coalesce(cr_return_amount, 0)) returns,
                sum(cs_net_profit - coalesce(cr_net_loss, 0)) profit
         from catalog_sales left join catalog_returns
              on cs_item_sk = cr_item_sk
              and cs_order_number = cr_order_number,
              date_dim, catalog_page, item, promotion
         where cs_sold_date_sk = d_date_sk
           and d_date between date '2000-08-23'
               and date '2000-08-23' + interval '30' day
           and cs_catalog_page_sk = cp_catalog_page_sk
           and cs_item_sk = i_item_sk
           and i_current_price > 50
           and cs_promo_sk = p_promo_sk
           and p_channel_tv = 'N'
         group by cp_catalog_page_id) csr
   union all
   select 'web channel', 'web_site' || web_id, sales, returns, profit
   from (select web_site_id web_id, sum(ws_ext_sales_price) sales,
                sum(coalesce(wr_return_amt, 0)) returns,
                sum(ws_net_profit - coalesce(wr_net_loss, 0)) profit
         from web_sales left join web_returns
              on ws_item_sk = wr_item_sk
              and ws_order_number = wr_order_number,
              date_dim, web_site, item, promotion
         where ws_sold_date_sk = d_date_sk
           and d_date between date '2000-08-23'
               and date '2000-08-23' + interval '30' day
           and ws_web_site_sk = web_site_sk
           and ws_item_sk = i_item_sk
           and i_current_price > 50
           and ws_promo_sk = p_promo_sk
           and p_channel_tv = 'N'
         group by web_site_id) wsr
"""

QUERIES["q80"] = f"""
select channel, id, sum(sales) sales, sum(returns) returns,
       sum(profit) profit
from ({_Q80_CHANNELS}) x
group by rollup(channel, id)
order by channel, id
limit 100
"""

SQLITE_ORACLE["q80"] = (
    _rollup_union(
        ["channel", "id"],
        "sum(sales) sales, sum(returns) returns, sum(profit) profit",
        f"from ({_Q80_CHANNELS}) x",
    )
    + " order by 1 nulls last, 2 nulls last limit 100"
)

_Q77_BODY = """
with ss as (
  select s_store_sk, sum(ss_ext_sales_price) sales,
         sum(ss_net_profit) profit
  from store_sales, date_dim, store
  where ss_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '30' day
    and ss_store_sk = s_store_sk
  group by s_store_sk),
sr as (
  select sr_store_sk s_store_sk, sum(sr_return_amt) returns,
         sum(sr_net_loss) profit_loss
  from store_returns, date_dim, store
  where sr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '30' day
    and sr_store_sk = s_store_sk
  group by sr_store_sk),
cs as (
  select cs_call_center_sk, sum(cs_ext_sales_price) sales,
         sum(cs_net_profit) profit
  from catalog_sales, date_dim
  where cs_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '30' day
  group by cs_call_center_sk),
cr as (
  select cr_call_center_sk, sum(cr_return_amount) returns,
         sum(cr_net_loss) profit_loss
  from catalog_returns, date_dim
  where cr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '30' day
  group by cr_call_center_sk),
ws as (
  select wp_web_page_sk, sum(ws_ext_sales_price) sales,
         sum(ws_net_profit) profit
  from web_sales, date_dim, web_page
  where ws_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '30' day
    and ws_web_page_sk = wp_web_page_sk
  group by wp_web_page_sk),
wr as (
  select wr_web_page_sk wp_web_page_sk, sum(wr_return_amt) returns,
         sum(wr_net_loss) profit_loss
  from web_returns, date_dim, web_page
  where wr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '30' day
    and wr_web_page_sk = wp_web_page_sk
  group by wr_web_page_sk)
"""

_Q77_UNION = """
   select 'store channel' channel, ss.s_store_sk id, sales,
          coalesce(returns, 0) returns,
          profit - coalesce(profit_loss, 0) profit
   from ss left join sr on ss.s_store_sk = sr.s_store_sk
   union all
   select 'catalog channel', cs_call_center_sk, sales, returns,
          profit - profit_loss
   from cs, cr
   union all
   select 'web channel', ws.wp_web_page_sk, sales,
          coalesce(returns, 0) returns,
          profit - coalesce(profit_loss, 0) profit
   from ws left join wr on ws.wp_web_page_sk = wr.wp_web_page_sk
"""

QUERIES["q77"] = f"""
{_Q77_BODY}
select channel, id, sum(sales) sales, sum(returns) returns,
       sum(profit) profit
from ({_Q77_UNION}) x
group by rollup(channel, id)
order by channel, id, sales
limit 100
"""

SQLITE_ORACLE["q77"] = (
    _Q77_BODY
    + _rollup_union(
        ["channel", "id"],
        "sum(sales) sales, sum(returns) returns, sum(profit) profit",
        f"from ({_Q77_UNION}) x",
    )
    + " order by 1 nulls last, 2 nulls last, 3 limit 100"
)

_Q5_BODY = """
with ssr as (
  select s_store_id, sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns, sum(net_loss) profit_loss
  from (select ss_store_sk store_sk, ss_sold_date_sk date_sk,
               ss_ext_sales_price sales_price, ss_net_profit profit,
               cast(0 as decimal(7,2)) return_amt,
               cast(0 as decimal(7,2)) net_loss
        from store_sales
        union all
        select sr_store_sk, sr_returned_date_sk,
               cast(0 as decimal(7,2)), cast(0 as decimal(7,2)),
               sr_return_amt, sr_net_loss
        from store_returns) salesreturns, date_dim, store
  where date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '14' day
    and store_sk = s_store_sk
  group by s_store_id),
csr as (
  select cp_catalog_page_id, sum(sales_price) sales,
         sum(profit) profit, sum(return_amt) returns,
         sum(net_loss) profit_loss
  from (select cs_catalog_page_sk page_sk, cs_sold_date_sk date_sk,
               cs_ext_sales_price sales_price, cs_net_profit profit,
               cast(0 as decimal(7,2)) return_amt,
               cast(0 as decimal(7,2)) net_loss
        from catalog_sales
        union all
        select cr_catalog_page_sk, cr_returned_date_sk,
               cast(0 as decimal(7,2)), cast(0 as decimal(7,2)),
               cr_return_amount, cr_net_loss
        from catalog_returns) salesreturns, date_dim, catalog_page
  where date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '14' day
    and page_sk = cp_catalog_page_sk
  group by cp_catalog_page_id),
wsr as (
  select web_site_id, sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns, sum(net_loss) profit_loss
  from (select ws_web_site_sk wsr_web_site_sk, ws_sold_date_sk date_sk,
               ws_ext_sales_price sales_price, ws_net_profit profit,
               cast(0 as decimal(7,2)) return_amt,
               cast(0 as decimal(7,2)) net_loss
        from web_sales
        union all
        select ws_web_site_sk, wr_returned_date_sk,
               cast(0 as decimal(7,2)), cast(0 as decimal(7,2)),
               wr_return_amt, wr_net_loss
        from web_returns left join web_sales
             on wr_item_sk = ws_item_sk
             and wr_order_number = ws_order_number) salesreturns,
       date_dim, web_site
  where date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '14' day
    and wsr_web_site_sk = web_site_sk
  group by web_site_id)
"""

_Q5_UNION = """
   select 'store channel' channel, 'store' || s_store_id id, sales,
          returns, profit - profit_loss profit
   from ssr
   union all
   select 'catalog channel', 'catalog_page' || cp_catalog_page_id,
          sales, returns, profit - profit_loss
   from csr
   union all
   select 'web channel', 'web_site' || web_site_id, sales, returns,
          profit - profit_loss
   from wsr
"""

QUERIES["q5"] = f"""
{_Q5_BODY}
select channel, id, sum(sales) sales, sum(returns) returns,
       sum(profit) profit
from ({_Q5_UNION}) x
group by rollup(channel, id)
order by channel, id
limit 100
"""

SQLITE_ORACLE["q5"] = (
    _Q5_BODY
    + _rollup_union(
        ["channel", "id"],
        "sum(sales) sales, sum(returns) returns, sum(profit) profit",
        f"from ({_Q5_UNION}) x",
    )
    + " order by 1 nulls last, 2 nulls last limit 100"
)


# ---- round-5 additions ----------------------------------------------------
# Canonical spec queries (benchmark definition set, restated in the
# engine dialect with single-token aliases; reference:
# testing/trino-benchmark-queries/.../sql/trino/tpcds/q*.sql).

QUERIES["q1"] = """
WITH
  customer_total_return AS (
   SELECT
     sr_customer_sk ctr_customer_sk
   , sr_store_sk ctr_store_sk
   , sum(sr_return_amt) ctr_total_return
   FROM
     store_returns
   , date_dim
   WHERE (sr_returned_date_sk = d_date_sk)
      AND (d_year = 2000)
   GROUP BY sr_customer_sk, sr_store_sk
) 
SELECT c_customer_id
FROM
  customer_total_return ctr1
, store
, customer
WHERE (ctr1.ctr_total_return > (
      SELECT (avg(ctr_total_return) * 1.2)
      FROM
        customer_total_return ctr2
      WHERE (ctr1.ctr_store_sk = ctr2.ctr_store_sk)
   ))
   AND (s_store_sk = ctr1.ctr_store_sk)
   AND (s_state = 'TN')
   AND (ctr1.ctr_customer_sk = c_customer_sk)
ORDER BY c_customer_id ASC
LIMIT 100
"""

QUERIES["q2"] = """
WITH
  wscs AS (
   SELECT
     sold_date_sk
   , sales_price
   FROM
     (
      SELECT
        ws_sold_date_sk sold_date_sk
      , ws_ext_sales_price sales_price
      FROM
        web_sales
   )  
UNION ALL (
      SELECT
        cs_sold_date_sk sold_date_sk
      , cs_ext_sales_price sales_price
      FROM
        catalog_sales
   ) ) 
, wswscs AS (
   SELECT
     d_week_seq
   , sum((CASE WHEN (d_day_name = 'Sunday') THEN sales_price ELSE null END)) sun_sales
   , sum((CASE WHEN (d_day_name = 'Monday') THEN sales_price ELSE null END)) mon_sales
   , sum((CASE WHEN (d_day_name = 'Tuesday') THEN sales_price ELSE null END)) tue_sales
   , sum((CASE WHEN (d_day_name = 'Wednesday') THEN sales_price ELSE null END)) wed_sales
   , sum((CASE WHEN (d_day_name = 'Thursday') THEN sales_price ELSE null END)) thu_sales
   , sum((CASE WHEN (d_day_name = 'Friday') THEN sales_price ELSE null END)) fri_sales
   , sum((CASE WHEN (d_day_name = 'Saturday') THEN sales_price ELSE null END)) sat_sales
   FROM
     wscs
   , date_dim
   WHERE (d_date_sk = sold_date_sk)
   GROUP BY d_week_seq
) 
SELECT
  d_week_seq1
, round((sun_sales1 / sun_sales2), 2)
, round((mon_sales1 / mon_sales2), 2)
, round((tue_sales1 / tue_sales2), 2)
, round((wed_sales1 / wed_sales2), 2)
, round((thu_sales1 / thu_sales2), 2)
, round((fri_sales1 / fri_sales2), 2)
, round((sat_sales1 / sat_sales2), 2)
FROM
  (
   SELECT
     wswscs.d_week_seq d_week_seq1
   , sun_sales sun_sales1
   , mon_sales mon_sales1
   , tue_sales tue_sales1
   , wed_sales wed_sales1
   , thu_sales thu_sales1
   , fri_sales fri_sales1
   , sat_sales sat_sales1
   FROM
     wswscs
   , date_dim
   WHERE (date_dim.d_week_seq = wswscs.d_week_seq)
      AND (d_year = 2001)
)  y
, (
   SELECT
     wswscs.d_week_seq d_week_seq2
   , sun_sales sun_sales2
   , mon_sales mon_sales2
   , tue_sales tue_sales2
   , wed_sales wed_sales2
   , thu_sales thu_sales2
   , fri_sales fri_sales2
   , sat_sales sat_sales2
   FROM
     wswscs
   , date_dim
   WHERE (date_dim.d_week_seq = wswscs.d_week_seq)
      AND (d_year = (2001 + 1))
)  z
WHERE (d_week_seq1 = (d_week_seq2 - 53))
ORDER BY d_week_seq1 ASC
"""

QUERIES["q4"] = """
WITH
  year_total AS (
   SELECT
     c_customer_id customer_id
   , c_first_name customer_first_name
   , c_last_name customer_last_name
   , c_preferred_cust_flag customer_preferred_cust_flag
   , c_birth_country customer_birth_country
   , c_login customer_login
   , c_email_address customer_email_address
   , d_year dyear
   , sum(((((ss_ext_list_price - ss_ext_wholesale_cost) - ss_ext_discount_amt) + ss_ext_sales_price) / 2)) year_total
   , 's' sale_type
   FROM
     customer
   , store_sales
   , date_dim
   WHERE (c_customer_sk = ss_customer_sk)
      AND (ss_sold_date_sk = d_date_sk)
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag, c_birth_country, c_login, c_email_address, d_year
UNION ALL    SELECT
     c_customer_id customer_id
   , c_first_name customer_first_name
   , c_last_name customer_last_name
   , c_preferred_cust_flag customer_preferred_cust_flag
   , c_birth_country customer_birth_country
   , c_login customer_login
   , c_email_address customer_email_address
   , d_year dyear
   , sum(((((cs_ext_list_price - cs_ext_wholesale_cost) - cs_ext_discount_amt) + cs_ext_sales_price) / 2)) year_total
   , 'c' sale_type
   FROM
     customer
   , catalog_sales
   , date_dim
   WHERE (c_customer_sk = cs_bill_customer_sk)
      AND (cs_sold_date_sk = d_date_sk)
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag, c_birth_country, c_login, c_email_address, d_year
UNION ALL    SELECT
     c_customer_id customer_id
   , c_first_name customer_first_name
   , c_last_name customer_last_name
   , c_preferred_cust_flag customer_preferred_cust_flag
   , c_birth_country customer_birth_country
   , c_login customer_login
   , c_email_address customer_email_address
   , d_year dyear
   , sum(((((ws_ext_list_price - ws_ext_wholesale_cost) - ws_ext_discount_amt) + ws_ext_sales_price) / 2)) year_total
   , 'w' sale_type
   FROM
     customer
   , web_sales
   , date_dim
   WHERE (c_customer_sk = ws_bill_customer_sk)
      AND (ws_sold_date_sk = d_date_sk)
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag, c_birth_country, c_login, c_email_address, d_year
) 
SELECT
  t_s_secyear.customer_id
, t_s_secyear.customer_first_name
, t_s_secyear.customer_last_name
, t_s_secyear.customer_preferred_cust_flag
FROM
  year_total t_s_firstyear
, year_total t_s_secyear
, year_total t_c_firstyear
, year_total t_c_secyear
, year_total t_w_firstyear
, year_total t_w_secyear
WHERE (t_s_secyear.customer_id = t_s_firstyear.customer_id)
   AND (t_s_firstyear.customer_id = t_c_secyear.customer_id)
   AND (t_s_firstyear.customer_id = t_c_firstyear.customer_id)
   AND (t_s_firstyear.customer_id = t_w_firstyear.customer_id)
   AND (t_s_firstyear.customer_id = t_w_secyear.customer_id)
   AND (t_s_firstyear.sale_type = 's')
   AND (t_c_firstyear.sale_type = 'c')
   AND (t_w_firstyear.sale_type = 'w')
   AND (t_s_secyear.sale_type = 's')
   AND (t_c_secyear.sale_type = 'c')
   AND (t_w_secyear.sale_type = 'w')
   AND (t_s_firstyear.dyear = 2001)
   AND (t_s_secyear.dyear = (2001 + 1))
   AND (t_c_firstyear.dyear = 2001)
   AND (t_c_secyear.dyear = (2001 + 1))
   AND (t_w_firstyear.dyear = 2001)
   AND (t_w_secyear.dyear = (2001 + 1))
   AND (t_s_firstyear.year_total > 0)
   AND (t_c_firstyear.year_total > 0)
   AND (t_w_firstyear.year_total > 0)
   AND ((CASE WHEN (t_c_firstyear.year_total > 0) THEN (t_c_secyear.year_total / t_c_firstyear.year_total) ELSE null END) > (CASE WHEN (t_s_firstyear.year_total > 0) THEN (t_s_secyear.year_total / t_s_firstyear.year_total) ELSE null END))
   AND ((CASE WHEN (t_c_firstyear.year_total > 0) THEN (t_c_secyear.year_total / t_c_firstyear.year_total) ELSE null END) > (CASE WHEN (t_w_firstyear.year_total > 0) THEN (t_w_secyear.year_total / t_w_firstyear.year_total) ELSE null END))
ORDER BY t_s_secyear.customer_id ASC, t_s_secyear.customer_first_name ASC, t_s_secyear.customer_last_name ASC, t_s_secyear.customer_preferred_cust_flag ASC
LIMIT 100
"""

QUERIES["q6"] = """
SELECT
  a.ca_state state_
, count(*) cnt
FROM
  customer_address a
, customer c
, store_sales s
, date_dim d
, item i
WHERE (a.ca_address_sk = c.c_current_addr_sk)
   AND (c.c_customer_sk = s.ss_customer_sk)
   AND (s.ss_sold_date_sk = d.d_date_sk)
   AND (s.ss_item_sk = i.i_item_sk)
   AND (d.d_month_seq = (
      SELECT DISTINCT d_month_seq
      FROM
        date_dim
      WHERE (d_year = 2001)
         AND (d_moy = 1)
   ))
   AND (i.i_current_price > (1.2 * (
         SELECT avg(j.i_current_price)
         FROM
           item j
         WHERE (j.i_category = i.i_category)
      )))
GROUP BY a.ca_state
HAVING (count(*) >= 10)
ORDER BY cnt ASC, a.ca_state ASC
LIMIT 100
"""

QUERIES["q8"] = """
SELECT
  s_store_name
, sum(ss_net_profit)
FROM
  store_sales
, date_dim
, store
, (
   SELECT ca_zip
   FROM
     (
(
         SELECT substr(ca_zip, 1, 5) ca_zip
         FROM
           customer_address
         WHERE (substr(ca_zip, 1, 5) IN (
                '24128'
              , '57834'
              , '13354'
              , '15734'
              , '78668'
              , '76232'
              , '62878'
              , '45375'
              , '63435'
              , '22245'
              , '65084'
              , '49130'
              , '40558'
              , '25733'
              , '15798'
              , '87816'
              , '81096'
              , '56458'
              , '35474'
              , '27156'
              , '83926'
              , '18840'
              , '28286'
              , '24676'
              , '37930'
              , '77556'
              , '27700'
              , '45266'
              , '94627'
              , '62971'
              , '20548'
              , '23470'
              , '47305'
              , '53535'
              , '21337'
              , '26231'
              , '50412'
              , '69399'
              , '17879'
              , '51622'
              , '43848'
              , '21195'
              , '83921'
              , '15559'
              , '67853'
              , '15126'
              , '16021'
              , '26233'
              , '53268'
              , '10567'
              , '91137'
              , '76107'
              , '11101'
              , '59166'
              , '38415'
              , '61265'
              , '71954'
              , '15371'
              , '11928'
              , '15455'
              , '98294'
              , '68309'
              , '69913'
              , '59402'
              , '58263'
              , '25782'
              , '18119'
              , '35942'
              , '33282'
              , '42029'
              , '17920'
              , '98359'
              , '15882'
              , '45721'
              , '60279'
              , '18426'
              , '64544'
              , '25631'
              , '43933'
              , '37125'
              , '98235'
              , '10336'
              , '24610'
              , '68101'
              , '56240'
              , '40081'
              , '86379'
              , '44165'
              , '33515'
              , '88190'
              , '84093'
              , '27068'
              , '99076'
              , '36634'
              , '50308'
              , '28577'
              , '39736'
              , '33786'
              , '71286'
              , '26859'
              , '55565'
              , '98569'
              , '70738'
              , '19736'
              , '64457'
              , '17183'
              , '28915'
              , '26653'
              , '58058'
              , '89091'
              , '54601'
              , '24206'
              , '14328'
              , '55253'
              , '82136'
              , '67897'
              , '56529'
              , '72305'
              , '67473'
              , '62377'
              , '22752'
              , '57647'
              , '62496'
              , '41918'
              , '36233'
              , '86284'
              , '54917'
              , '22152'
              , '19515'
              , '63837'
              , '18376'
              , '42961'
              , '10144'
              , '36495'
              , '58078'
              , '38607'
              , '91110'
              , '64147'
              , '19430'
              , '17043'
              , '45200'
              , '63981'
              , '48425'
              , '22351'
              , '30010'
              , '21756'
              , '14922'
              , '14663'
              , '77191'
              , '60099'
              , '29741'
              , '36420'
              , '21076'
              , '91393'
              , '28810'
              , '96765'
              , '23006'
              , '18799'
              , '49156'
              , '98025'
              , '23932'
              , '67467'
              , '30450'
              , '50298'
              , '29178'
              , '89360'
              , '32754'
              , '63089'
              , '87501'
              , '87343'
              , '29839'
              , '30903'
              , '81019'
              , '18652'
              , '73273'
              , '25989'
              , '20260'
              , '68893'
              , '53179'
              , '30469'
              , '28898'
              , '31671'
              , '24996'
              , '18767'
              , '64034'
              , '91068'
              , '51798'
              , '51200'
              , '63193'
              , '39516'
              , '72550'
              , '72325'
              , '51211'
              , '23968'
              , '86057'
              , '10390'
              , '85816'
              , '45692'
              , '65164'
              , '21309'
              , '18845'
              , '68621'
              , '92712'
              , '68880'
              , '90257'
              , '47770'
              , '13955'
              , '70466'
              , '21286'
              , '67875'
              , '82636'
              , '36446'
              , '79994'
              , '72823'
              , '40162'
              , '41367'
              , '41766'
              , '22437'
              , '58470'
              , '11356'
              , '76638'
              , '68806'
              , '25280'
              , '67301'
              , '73650'
              , '86198'
              , '16725'
              , '38935'
              , '13394'
              , '61810'
              , '81312'
              , '15146'
              , '71791'
              , '31016'
              , '72013'
              , '37126'
              , '22744'
              , '73134'
              , '70372'
              , '30431'
              , '39192'
              , '35850'
              , '56571'
              , '67030'
              , '22461'
              , '88424'
              , '88086'
              , '14060'
              , '40604'
              , '19512'
              , '72175'
              , '51649'
              , '19505'
              , '24317'
              , '13375'
              , '81426'
              , '18270'
              , '72425'
              , '45748'
              , '55307'
              , '53672'
              , '52867'
              , '56575'
              , '39127'
              , '30625'
              , '10445'
              , '39972'
              , '74351'
              , '26065'
              , '83849'
              , '42666'
              , '96976'
              , '68786'
              , '77721'
              , '68908'
              , '66864'
              , '63792'
              , '51650'
              , '31029'
              , '26689'
              , '66708'
              , '11376'
              , '20004'
              , '31880'
              , '96451'
              , '41248'
              , '94898'
              , '18383'
              , '60576'
              , '38193'
              , '48583'
              , '13595'
              , '76614'
              , '24671'
              , '46820'
              , '82276'
              , '10516'
              , '11634'
              , '45549'
              , '88885'
              , '18842'
              , '90225'
              , '18906'
              , '13376'
              , '84935'
              , '78890'
              , '58943'
              , '15765'
              , '50016'
              , '69035'
              , '49448'
              , '39371'
              , '41368'
              , '33123'
              , '83144'
              , '14089'
              , '94945'
              , '73241'
              , '19769'
              , '47537'
              , '38122'
              , '28587'
              , '76698'
              , '22927'
              , '56616'
              , '34425'
              , '96576'
              , '78567'
              , '97789'
              , '94983'
              , '79077'
              , '57855'
              , '97189'
              , '46081'
              , '48033'
              , '19849'
              , '28488'
              , '28545'
              , '72151'
              , '69952'
              , '43285'
              , '26105'
              , '76231'
              , '15723'
              , '25486'
              , '39861'
              , '83933'
              , '75691'
              , '46136'
              , '61547'
              , '66162'
              , '25858'
              , '22246'
              , '51949'
              , '27385'
              , '77610'
              , '34322'
              , '51061'
              , '68100'
              , '61860'
              , '13695'
              , '44438'
              , '90578'
              , '96888'
              , '58048'
              , '99543'
              , '73171'
              , '56691'
              , '64528'
              , '56910'
              , '83444'
              , '30122'
              , '68014'
              , '14171'
              , '16807'
              , '83041'
              , '34102'
              , '51103'
              , '79777'
              , '17871'
              , '12305'
              , '22685'
              , '94167'
              , '28709'
              , '35258'
              , '57665'
              , '71256'
              , '57047'
              , '11489'
              , '31387'
              , '68341'
              , '78451'
              , '14867'
              , '25103'
              , '35458'
              , '25003'
              , '54364'
              , '73520'
              , '32213'
              , '35576'))
      )       INTERSECT (
         SELECT ca_zip
         FROM
           (
            SELECT
              substr(ca_zip, 1, 5) ca_zip
            , count(*) cnt
            FROM
              customer_address
            , customer
            WHERE (ca_address_sk = c_current_addr_sk)
               AND (c_preferred_cust_flag = 'Y')
            GROUP BY ca_zip
            HAVING (count(*) > 10)
         )  a1
      )    )  a2
)  v1
WHERE (ss_store_sk = s_store_sk)
   AND (ss_sold_date_sk = d_date_sk)
   AND (d_qoy = 2)
   AND (d_year = 1998)
   AND (substr(s_zip, 1, 2) = substr(v1.ca_zip, 1, 2))
GROUP BY s_store_name
ORDER BY s_store_name ASC
LIMIT 100
"""

QUERIES["q11"] = """
WITH
  year_total AS (
   SELECT
     c_customer_id customer_id
   , c_first_name customer_first_name
   , c_last_name customer_last_name
   , c_preferred_cust_flag customer_preferred_cust_flag
   , c_birth_country customer_birth_country
   , c_login customer_login
   , c_email_address customer_email_address
   , d_year dyear
   , sum((ss_ext_list_price - ss_ext_discount_amt)) year_total
   , 's' sale_type
   FROM
     customer
   , store_sales
   , date_dim
   WHERE (c_customer_sk = ss_customer_sk)
      AND (ss_sold_date_sk = d_date_sk)
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag, c_birth_country, c_login, c_email_address, d_year
UNION ALL    SELECT
     c_customer_id customer_id
   , c_first_name customer_first_name
   , c_last_name customer_last_name
   , c_preferred_cust_flag customer_preferred_cust_flag
   , c_birth_country customer_birth_country
   , c_login customer_login
   , c_email_address customer_email_address
   , d_year dyear
   , sum((ws_ext_list_price - ws_ext_discount_amt)) year_total
   , 'w' sale_type
   FROM
     customer
   , web_sales
   , date_dim
   WHERE (c_customer_sk = ws_bill_customer_sk)
      AND (ws_sold_date_sk = d_date_sk)
   GROUP BY c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag, c_birth_country, c_login, c_email_address, d_year
) 
SELECT
  t_s_secyear.customer_id
, t_s_secyear.customer_first_name
, t_s_secyear.customer_last_name
, t_s_secyear.customer_preferred_cust_flag
, t_s_secyear.customer_birth_country
, t_s_secyear.customer_login
FROM
  year_total t_s_firstyear
, year_total t_s_secyear
, year_total t_w_firstyear
, year_total t_w_secyear
WHERE (t_s_secyear.customer_id = t_s_firstyear.customer_id)
   AND (t_s_firstyear.customer_id = t_w_secyear.customer_id)
   AND (t_s_firstyear.customer_id = t_w_firstyear.customer_id)
   AND (t_s_firstyear.sale_type = 's')
   AND (t_w_firstyear.sale_type = 'w')
   AND (t_s_secyear.sale_type = 's')
   AND (t_w_secyear.sale_type = 'w')
   AND (t_s_firstyear.dyear = 2001)
   AND (t_s_secyear.dyear = (2001 + 1))
   AND (t_w_firstyear.dyear = 2001)
   AND (t_w_secyear.dyear = (2001 + 1))
   AND (t_s_firstyear.year_total > 0)
   AND (t_w_firstyear.year_total > 0)
   AND ((CASE WHEN (t_w_firstyear.year_total > 0) THEN (t_w_secyear.year_total / t_w_firstyear.year_total) ELSE 0.0 END) > (CASE WHEN (t_s_firstyear.year_total > 0) THEN (t_s_secyear.year_total / t_s_firstyear.year_total) ELSE 0.0 END))
ORDER BY t_s_secyear.customer_id ASC, t_s_secyear.customer_first_name ASC, t_s_secyear.customer_last_name ASC, t_s_secyear.customer_preferred_cust_flag ASC
LIMIT 100
"""

QUERIES["q16"] = """
SELECT
  count(DISTINCT cs_order_number) order_count
, sum(cs_ext_ship_cost) total_shipping_cost
, sum(cs_net_profit) total_net_profit
FROM
  catalog_sales cs1
, date_dim
, customer_address
, call_center
WHERE (d_date BETWEEN CAST('2002-2-01' AS DATE) AND (CAST('2002-2-01' AS DATE) + INTERVAL  '60' DAY))
   AND (cs1.cs_ship_date_sk = d_date_sk)
   AND (cs1.cs_ship_addr_sk = ca_address_sk)
   AND (ca_state = 'GA')
   AND (cs1.cs_call_center_sk = cc_call_center_sk)
   AND (cc_county IN ('Williamson County', 'Williamson County', 'Williamson County', 'Williamson County', 'Williamson County'))
   AND (EXISTS (
   SELECT *
   FROM
     catalog_sales cs2
   WHERE (cs1.cs_order_number = cs2.cs_order_number)
      AND (cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
))
   AND (NOT (EXISTS (
   SELECT *
   FROM
     catalog_returns cr1
   WHERE (cs1.cs_order_number = cr1.cr_order_number)
)))
ORDER BY count(DISTINCT cs_order_number) ASC
LIMIT 100
"""

QUERIES["q17"] = """
SELECT
  i_item_id
, i_item_desc
, s_state
, count(ss_quantity) store_sales_quantitycount
, avg(ss_quantity) store_sales_quantityave
, stddev_samp(ss_quantity) store_sales_quantitystdev
, (stddev_samp(ss_quantity) / avg(ss_quantity)) store_sales_quantitycov
, count(sr_return_quantity) store_returns_quantitycount
, avg(sr_return_quantity) store_returns_quantityave
, stddev_samp(sr_return_quantity) store_returns_quantitystdev
, (stddev_samp(sr_return_quantity) / avg(sr_return_quantity)) store_returns_quantitycov
, count(cs_quantity) catalog_sales_quantitycount
, avg(cs_quantity) catalog_sales_quantityave
, stddev_samp(cs_quantity) catalog_sales_quantitystdev
, (stddev_samp(cs_quantity) / avg(cs_quantity)) catalog_sales_quantitycov
FROM
  store_sales
, store_returns
, catalog_sales
, date_dim d1
, date_dim d2
, date_dim d3
, store
, item
WHERE (d1.d_quarter_name = '2001Q1')
   AND (d1.d_date_sk = ss_sold_date_sk)
   AND (i_item_sk = ss_item_sk)
   AND (s_store_sk = ss_store_sk)
   AND (ss_customer_sk = sr_customer_sk)
   AND (ss_item_sk = sr_item_sk)
   AND (ss_ticket_number = sr_ticket_number)
   AND (sr_returned_date_sk = d2.d_date_sk)
   AND (d2.d_quarter_name IN ('2001Q1', '2001Q2', '2001Q3'))
   AND (sr_customer_sk = cs_bill_customer_sk)
   AND (sr_item_sk = cs_item_sk)
   AND (cs_sold_date_sk = d3.d_date_sk)
   AND (d3.d_quarter_name IN ('2001Q1', '2001Q2', '2001Q3'))
GROUP BY i_item_id, i_item_desc, s_state
ORDER BY i_item_id ASC, i_item_desc ASC, s_state ASC
LIMIT 100
"""

QUERIES["q21"] = """
SELECT *
FROM
  (
   SELECT
     w_warehouse_name
   , i_item_id
   , sum((CASE WHEN (CAST(d_date AS DATE) < CAST('2000-03-11' AS DATE)) THEN inv_quantity_on_hand ELSE 0 END)) inv_before
   , sum((CASE WHEN (CAST(d_date AS DATE) >= CAST('2000-03-11' AS DATE)) THEN inv_quantity_on_hand ELSE 0 END)) inv_after
   FROM
     inventory
   , warehouse
   , item
   , date_dim
   WHERE (i_current_price BETWEEN 0.99 AND 1.49)
      AND (i_item_sk = inv_item_sk)
      AND (inv_warehouse_sk = w_warehouse_sk)
      AND (inv_date_sk = d_date_sk)
      AND (d_date BETWEEN (CAST('2000-03-11' AS DATE) - INTERVAL  '30' DAY) AND (CAST('2000-03-11' AS DATE) + INTERVAL  '30' DAY))
   GROUP BY w_warehouse_name, i_item_id
)  x
WHERE ((CASE WHEN (inv_before > 0) THEN (CAST(inv_after AS DECIMAL(7,2)) / inv_before) ELSE null END) BETWEEN (2.00 / 3.00) AND (3.00 / 2.00))
ORDER BY w_warehouse_name ASC, i_item_id ASC
LIMIT 100
"""

QUERIES["q23"] = """
WITH
  frequent_ss_items AS (
   SELECT
     substr(i_item_desc, 1, 30) itemdesc
   , i_item_sk item_sk
   , d_date solddate
   , count(*) cnt
   FROM
     store_sales
   , date_dim
   , item
   WHERE (ss_sold_date_sk = d_date_sk)
      AND (ss_item_sk = i_item_sk)
      AND (d_year IN (2000   , (2000 + 1)   , (2000 + 2)   , (2000 + 3)))
   GROUP BY substr(i_item_desc, 1, 30), i_item_sk, d_date
   HAVING (count(*) > 4)
) 
, max_store_sales AS (
   SELECT max(csales) tpcds_cmax
   FROM
     (
      SELECT
        c_customer_sk
      , sum((ss_quantity * ss_sales_price)) csales
      FROM
        store_sales
      , customer
      , date_dim
      WHERE (ss_customer_sk = c_customer_sk)
         AND (ss_sold_date_sk = d_date_sk)
         AND (d_year IN (2000      , (2000 + 1)      , (2000 + 2)      , (2000 + 3)))
      GROUP BY c_customer_sk
   ) 
) 
, best_ss_customer AS (
   SELECT
     c_customer_sk
   , sum((ss_quantity * ss_sales_price)) ssales
   FROM
     store_sales
   , customer
   WHERE (ss_customer_sk = c_customer_sk)
   GROUP BY c_customer_sk
   HAVING (sum((ss_quantity * ss_sales_price)) > ((50 / 100.0) * (
            SELECT *
            FROM
              max_store_sales
         )))
) 
SELECT sum(sales)
FROM
  (
   SELECT (cs_quantity * cs_list_price) sales
   FROM
     catalog_sales
   , date_dim
   WHERE (d_year = 2000)
      AND (d_moy = 2)
      AND (cs_sold_date_sk = d_date_sk)
      AND (cs_item_sk IN (
      SELECT item_sk
      FROM
        frequent_ss_items
   ))
      AND (cs_bill_customer_sk IN (
      SELECT c_customer_sk
      FROM
        best_ss_customer
   ))
UNION ALL    SELECT (ws_quantity * ws_list_price) sales
   FROM
     web_sales
   , date_dim
   WHERE (d_year = 2000)
      AND (d_moy = 2)
      AND (ws_sold_date_sk = d_date_sk)
      AND (ws_item_sk IN (
      SELECT item_sk
      FROM
        frequent_ss_items
   ))
      AND (ws_bill_customer_sk IN (
      SELECT c_customer_sk
      FROM
        best_ss_customer
   ))
) 
LIMIT 100
"""

QUERIES["q24"] = """
WITH
  ssales AS (
   SELECT
     c_last_name
   , c_first_name
   , s_store_name
   , ca_state
   , s_state
   , i_color
   , i_current_price
   , i_manager_id
   , i_units
   , i_size
   , sum(ss_net_paid) netpaid
   FROM
     store_sales
   , store_returns
   , store
   , item
   , customer
   , customer_address
   WHERE (ss_ticket_number = sr_ticket_number)
      AND (ss_item_sk = sr_item_sk)
      AND (ss_customer_sk = c_customer_sk)
      AND (ss_item_sk = i_item_sk)
      AND (ss_store_sk = s_store_sk)
      AND (c_birth_country = upper(ca_country))
      AND (s_zip = ca_zip)
      AND (s_market_id = 8)
   GROUP BY c_last_name, c_first_name, s_store_name, ca_state, s_state, i_color, i_current_price, i_manager_id, i_units, i_size
)
SELECT
  c_last_name
, c_first_name
, s_store_name
, sum(netpaid) paid
FROM
  ssales
WHERE (i_color = 'pale')
GROUP BY c_last_name, c_first_name, s_store_name
HAVING (sum(netpaid) > (
      SELECT (0.05 * avg(netpaid))
      FROM
        ssales
   ))
ORDER BY c_last_name, c_first_name, s_store_name
"""

QUERIES["q28"] = """
SELECT *
FROM
  (
   SELECT
     avg(ss_list_price) b1_lp
   , count(ss_list_price) b1_cnt
   , count(DISTINCT ss_list_price) b1_cntd
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 0 AND 5)
      AND ((ss_list_price BETWEEN 8 AND (8 + 10))
         OR (ss_coupon_amt BETWEEN 459 AND (459 + 1000))
         OR (ss_wholesale_cost BETWEEN 57 AND (57 + 20)))
)  b1
, (
   SELECT
     avg(ss_list_price) b2_lp
   , count(ss_list_price) b2_cnt
   , count(DISTINCT ss_list_price) b2_cntd
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 6 AND 10)
      AND ((ss_list_price BETWEEN 90 AND (90 + 10))
         OR (ss_coupon_amt BETWEEN 2323 AND (2323 + 1000))
         OR (ss_wholesale_cost BETWEEN 31 AND (31 + 20)))
)  b2
, (
   SELECT
     avg(ss_list_price) b3_lp
   , count(ss_list_price) b3_cnt
   , count(DISTINCT ss_list_price) b3_cntd
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 11 AND 15)
      AND ((ss_list_price BETWEEN 142 AND (142 + 10))
         OR (ss_coupon_amt BETWEEN 12214 AND (12214 + 1000))
         OR (ss_wholesale_cost BETWEEN 79 AND (79 + 20)))
)  b3
, (
   SELECT
     avg(ss_list_price) b4_lp
   , count(ss_list_price) b4_cnt
   , count(DISTINCT ss_list_price) b4_cntd
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 16 AND 20)
      AND ((ss_list_price BETWEEN 135 AND (135 + 10))
         OR (ss_coupon_amt BETWEEN 6071 AND (6071 + 1000))
         OR (ss_wholesale_cost BETWEEN 38 AND (38 + 20)))
)  b4
, (
   SELECT
     avg(ss_list_price) b5_lp
   , count(ss_list_price) b5_cnt
   , count(DISTINCT ss_list_price) b5_cntd
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 21 AND 25)
      AND ((ss_list_price BETWEEN 122 AND (122 + 10))
         OR (ss_coupon_amt BETWEEN 836 AND (836 + 1000))
         OR (ss_wholesale_cost BETWEEN 17 AND (17 + 20)))
)  b5
, (
   SELECT
     avg(ss_list_price) b6_lp
   , count(ss_list_price) b6_cnt
   , count(DISTINCT ss_list_price) b6_cntd
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 26 AND 30)
      AND ((ss_list_price BETWEEN 154 AND (154 + 10))
         OR (ss_coupon_amt BETWEEN 7326 AND (7326 + 1000))
         OR (ss_wholesale_cost BETWEEN 7 AND (7 + 20)))
)  b6
LIMIT 100
"""

QUERIES["q29"] = """
SELECT
  i_item_id
, i_item_desc
, s_store_id
, s_store_name
, sum(ss_quantity) store_sales_quantity
, sum(sr_return_quantity) store_returns_quantity
, sum(cs_quantity) catalog_sales_quantity
FROM
  store_sales
, store_returns
, catalog_sales
, date_dim d1
, date_dim d2
, date_dim d3
, store
, item
WHERE (d1.d_moy = 9)
   AND (d1.d_year = 1999)
   AND (d1.d_date_sk = ss_sold_date_sk)
   AND (i_item_sk = ss_item_sk)
   AND (s_store_sk = ss_store_sk)
   AND (ss_customer_sk = sr_customer_sk)
   AND (ss_item_sk = sr_item_sk)
   AND (ss_ticket_number = sr_ticket_number)
   AND (sr_returned_date_sk = d2.d_date_sk)
   AND (d2.d_moy BETWEEN 9 AND (9 + 3))
   AND (d2.d_year = 1999)
   AND (sr_customer_sk = cs_bill_customer_sk)
   AND (sr_item_sk = cs_item_sk)
   AND (cs_sold_date_sk = d3.d_date_sk)
   AND (d3.d_year IN (1999, (1999 + 1), (1999 + 2)))
GROUP BY i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY i_item_id ASC, i_item_desc ASC, s_store_id ASC, s_store_name ASC
LIMIT 100
"""

QUERIES["q30"] = """
WITH
  customer_total_return AS (
   SELECT
     wr_returning_customer_sk ctr_customer_sk
   , ca_state ctr_state
   , sum(wr_return_amt) ctr_total_return
   FROM
     web_returns
   , date_dim
   , customer_address
   WHERE (wr_returned_date_sk = d_date_sk)
      AND (d_year = 2002)
      AND (wr_returning_addr_sk = ca_address_sk)
   GROUP BY wr_returning_customer_sk, ca_state
) 
SELECT
  c_customer_id
, c_salutation
, c_first_name
, c_last_name
, c_preferred_cust_flag
, c_birth_day
, c_birth_month
, c_birth_year
, c_birth_country
, c_login
, c_email_address
, c_last_review_date_sk
, ctr_total_return
FROM
  customer_total_return ctr1
, customer_address
, customer
WHERE (ctr1.ctr_total_return > (
      SELECT (avg(ctr_total_return) * 1.2)
      FROM
        customer_total_return ctr2
      WHERE (ctr1.ctr_state = ctr2.ctr_state)
   ))
   AND (ca_address_sk = c_current_addr_sk)
   AND (ca_state = 'GA')
   AND (ctr1.ctr_customer_sk = c_customer_sk)
ORDER BY c_customer_id ASC, c_salutation ASC, c_first_name ASC, c_last_name ASC, c_preferred_cust_flag ASC, c_birth_day ASC, c_birth_month ASC, c_birth_year ASC, c_birth_country ASC, c_login ASC, c_email_address ASC, c_last_review_date_sk ASC, ctr_total_return ASC
LIMIT 100
"""

QUERIES["q31"] = """
WITH
  ss AS (
   SELECT
     ca_county
   , d_qoy
   , d_year
   , sum(ss_ext_sales_price) store_sales
   FROM
     store_sales
   , date_dim
   , customer_address
   WHERE (ss_sold_date_sk = d_date_sk)
      AND (ss_addr_sk = ca_address_sk)
   GROUP BY ca_county, d_qoy, d_year
) 
, ws AS (
   SELECT
     ca_county
   , d_qoy
   , d_year
   , sum(ws_ext_sales_price) web_sales
   FROM
     web_sales
   , date_dim
   , customer_address
   WHERE (ws_sold_date_sk = d_date_sk)
      AND (ws_bill_addr_sk = ca_address_sk)
   GROUP BY ca_county, d_qoy, d_year
) 
SELECT
  ss1.ca_county
, ss1.d_year
, (ws2.web_sales / ws1.web_sales) web_q1_q2_increase
, (ss2.store_sales / ss1.store_sales) store_q1_q2_increase
, (ws3.web_sales / ws2.web_sales) web_q2_q3_increase
, (ss3.store_sales / ss2.store_sales) store_q2_q3_increase
FROM
  ss ss1
, ss ss2
, ss ss3
, ws ws1
, ws ws2
, ws ws3
WHERE (ss1.d_qoy = 1)
   AND (ss1.d_year = 2000)
   AND (ss1.ca_county = ss2.ca_county)
   AND (ss2.d_qoy = 2)
   AND (ss2.d_year = 2000)
   AND (ss2.ca_county = ss3.ca_county)
   AND (ss3.d_qoy = 3)
   AND (ss3.d_year = 2000)
   AND (ss1.ca_county = ws1.ca_county)
   AND (ws1.d_qoy = 1)
   AND (ws1.d_year = 2000)
   AND (ws1.ca_county = ws2.ca_county)
   AND (ws2.d_qoy = 2)
   AND (ws2.d_year = 2000)
   AND (ws1.ca_county = ws3.ca_county)
   AND (ws3.d_qoy = 3)
   AND (ws3.d_year = 2000)
   AND ((CASE WHEN (ws1.web_sales > 0) THEN (CAST(ws2.web_sales AS DECIMAL(38,3)) / ws1.web_sales) ELSE null END) > (CASE WHEN (ss1.store_sales > 0) THEN (CAST(ss2.store_sales AS DECIMAL(38,3)) / ss1.store_sales) ELSE null END))
   AND ((CASE WHEN (ws2.web_sales > 0) THEN (CAST(ws3.web_sales AS DECIMAL(38,3)) / ws2.web_sales) ELSE null END) > (CASE WHEN (ss2.store_sales > 0) THEN (CAST(ss3.store_sales AS DECIMAL(38,3)) / ss2.store_sales) ELSE null END))
ORDER BY ss1.ca_county ASC
"""

QUERIES["q32"] = """
SELECT sum(cs_ext_discount_amt) excess_discount_amount
FROM
  catalog_sales
, item
, date_dim
WHERE (i_manufact_id = 977)
   AND (i_item_sk = cs_item_sk)
   AND (d_date BETWEEN CAST('2000-01-27' AS DATE) AND (CAST('2000-01-27' AS DATE) + INTERVAL  '90' DAY))
   AND (d_date_sk = cs_sold_date_sk)
   AND (cs_ext_discount_amt > (
      SELECT (1.3 * avg(cs_ext_discount_amt))
      FROM
        catalog_sales
      , date_dim
      WHERE (cs_item_sk = i_item_sk)
         AND (d_date BETWEEN CAST('2000-01-27' AS DATE) AND (CAST('2000-01-27' AS DATE) + INTERVAL  '90' DAY))
         AND (d_date_sk = cs_sold_date_sk)
   ))
LIMIT 100
"""

QUERIES["q33"] = """
WITH
  ss AS (
   SELECT
     i_manufact_id
   , sum(ss_ext_sales_price) total_sales
   FROM
     store_sales
   , date_dim
   , customer_address
   , item
   WHERE (i_manufact_id IN (
      SELECT i_manufact_id
      FROM
        item
      WHERE (i_category IN ('Electronics'))
   ))
      AND (ss_item_sk = i_item_sk)
      AND (ss_sold_date_sk = d_date_sk)
      AND (d_year = 1998)
      AND (d_moy = 5)
      AND (ss_addr_sk = ca_address_sk)
      AND (ca_gmt_offset = -5)
   GROUP BY i_manufact_id
) 
, cs AS (
   SELECT
     i_manufact_id
   , sum(cs_ext_sales_price) total_sales
   FROM
     catalog_sales
   , date_dim
   , customer_address
   , item
   WHERE (i_manufact_id IN (
      SELECT i_manufact_id
      FROM
        item
      WHERE (i_category IN ('Electronics'))
   ))
      AND (cs_item_sk = i_item_sk)
      AND (cs_sold_date_sk = d_date_sk)
      AND (d_year = 1998)
      AND (d_moy = 5)
      AND (cs_bill_addr_sk = ca_address_sk)
      AND (ca_gmt_offset = -5)
   GROUP BY i_manufact_id
) 
, ws AS (
   SELECT
     i_manufact_id
   , sum(ws_ext_sales_price) total_sales
   FROM
     web_sales
   , date_dim
   , customer_address
   , item
   WHERE (i_manufact_id IN (
      SELECT i_manufact_id
      FROM
        item
      WHERE (i_category IN ('Electronics'))
   ))
      AND (ws_item_sk = i_item_sk)
      AND (ws_sold_date_sk = d_date_sk)
      AND (d_year = 1998)
      AND (d_moy = 5)
      AND (ws_bill_addr_sk = ca_address_sk)
      AND (ca_gmt_offset = -5)
   GROUP BY i_manufact_id
) 
SELECT
  i_manufact_id
, sum(total_sales) total_sales
FROM
  (
   SELECT *
   FROM
     ss
UNION ALL    SELECT *
   FROM
     cs
UNION ALL    SELECT *
   FROM
     ws
)  tmp1
GROUP BY i_manufact_id
ORDER BY total_sales ASC
LIMIT 100
"""

QUERIES["q38"] = """
SELECT count(*)
FROM
  (
   SELECT DISTINCT
     c_last_name
   , c_first_name
   , d_date
   FROM
     store_sales
   , date_dim
   , customer
   WHERE (store_sales.ss_sold_date_sk = date_dim.d_date_sk)
      AND (store_sales.ss_customer_sk = customer.c_customer_sk)
      AND (d_month_seq BETWEEN 1200 AND (1200 + 11))
INTERSECT    SELECT DISTINCT
     c_last_name
   , c_first_name
   , d_date
   FROM
     catalog_sales
   , date_dim
   , customer
   WHERE (catalog_sales.cs_sold_date_sk = date_dim.d_date_sk)
      AND (catalog_sales.cs_bill_customer_sk = customer.c_customer_sk)
      AND (d_month_seq BETWEEN 1200 AND (1200 + 11))
INTERSECT    SELECT DISTINCT
     c_last_name
   , c_first_name
   , d_date
   FROM
     web_sales
   , date_dim
   , customer
   WHERE (web_sales.ws_sold_date_sk = date_dim.d_date_sk)
      AND (web_sales.ws_bill_customer_sk = customer.c_customer_sk)
      AND (d_month_seq BETWEEN 1200 AND (1200 + 11))
)  hot_cust
LIMIT 100
"""

QUERIES["q39"] = """
WITH
  inv AS (
   SELECT
     w_warehouse_name
   , w_warehouse_sk
   , i_item_sk
   , d_moy
   , stdev
   , mean
   , (CASE mean WHEN 0 THEN null ELSE (stdev / mean) END) cov
   FROM
     (
      SELECT
        w_warehouse_name
      , w_warehouse_sk
      , i_item_sk
      , d_moy
      , stddev_samp(inv_quantity_on_hand) stdev
      , avg(inv_quantity_on_hand) mean
      FROM
        inventory
      , item
      , warehouse
      , date_dim
      WHERE (inv_item_sk = i_item_sk)
         AND (inv_warehouse_sk = w_warehouse_sk)
         AND (inv_date_sk = d_date_sk)
         AND (d_year = 2001)
      GROUP BY w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy
   )  foo
   WHERE ((CASE mean WHEN 0 THEN 0 ELSE (stdev / mean) END) > 1)
) 
SELECT
  inv1.w_warehouse_sk
, inv1.i_item_sk
, inv1.d_moy
, inv1.mean
, CAST(inv1.cov AS DECIMAL(30, 10)) -- decrease precision to avoid unstable results due to roundings
, inv2.w_warehouse_sk
, inv2.i_item_sk
, inv2.d_moy
, inv2.mean
, CAST(inv2.cov AS DECIMAL(30, 10)) -- decrease precision to avoid unstable results due to roundings
FROM
  inv inv1
, inv inv2
WHERE (inv1.i_item_sk = inv2.i_item_sk)
   AND (inv1.w_warehouse_sk = inv2.w_warehouse_sk)
   AND (inv1.d_moy = 1)
   AND (inv2.d_moy = (1 + 1))
   AND (inv1.cov > 1.5)
ORDER BY inv1.w_warehouse_sk ASC, inv1.i_item_sk ASC, inv1.d_moy ASC, inv1.mean ASC, inv1.cov ASC, inv2.d_moy ASC, inv2.mean ASC, inv2.cov ASC
"""

QUERIES["q40"] = """
SELECT
  w_state
, i_item_id
, sum((CASE WHEN (CAST(d_date AS DATE) < CAST('2000-03-11' AS DATE)) THEN (cs_sales_price - COALESCE(cr_refunded_cash, 0)) ELSE 0 END)) sales_before
, sum((CASE WHEN (CAST(d_date AS DATE) >= CAST('2000-03-11' AS DATE)) THEN (cs_sales_price - COALESCE(cr_refunded_cash, 0)) ELSE 0 END)) sales_after
FROM
  (catalog_sales
LEFT JOIN catalog_returns ON (cs_order_number = cr_order_number)
   AND (cs_item_sk = cr_item_sk))
, warehouse
, item
, date_dim
WHERE (i_current_price BETWEEN 0.99 AND 1.49)
   AND (i_item_sk = cs_item_sk)
   AND (cs_warehouse_sk = w_warehouse_sk)
   AND (cs_sold_date_sk = d_date_sk)
   AND (CAST(d_date AS DATE) BETWEEN (CAST('2000-03-11' AS DATE) - INTERVAL  '30' DAY) AND (CAST('2000-03-11' AS DATE) + INTERVAL  '30' DAY))
GROUP BY w_state, i_item_id
ORDER BY w_state ASC, i_item_id ASC
LIMIT 100
"""

QUERIES["q44"] = """
SELECT
  asceding.rnk
, i1.i_product_name best_performing
, i2.i_product_name worst_performing
FROM
  (
   SELECT *
   FROM
     (
      SELECT
        item_sk
      , rank() OVER (ORDER BY rank_col ASC) rnk
      FROM
        (
         SELECT
           ss_item_sk item_sk
         , avg(ss_net_profit) rank_col
         FROM
           store_sales ss1
         WHERE (ss_store_sk = 4)
         GROUP BY ss_item_sk
         HAVING (avg(ss_net_profit) > (0.9 * (
                  SELECT avg(ss_net_profit) rank_col
                  FROM
                    store_sales
                  WHERE (ss_store_sk = 4)
                     AND (ss_addr_sk IS NULL)
                  GROUP BY ss_store_sk
               )))
      )  v1
   )  v11
   WHERE (rnk < 11)
)  asceding
, (
   SELECT *
   FROM
     (
      SELECT
        item_sk
      , rank() OVER (ORDER BY rank_col DESC) rnk
      FROM
        (
         SELECT
           ss_item_sk item_sk
         , avg(ss_net_profit) rank_col
         FROM
           store_sales ss1
         WHERE (ss_store_sk = 4)
         GROUP BY ss_item_sk
         HAVING (avg(ss_net_profit) > (0.9 * (
                  SELECT avg(ss_net_profit) rank_col
                  FROM
                    store_sales
                  WHERE (ss_store_sk = 4)
                     AND (ss_addr_sk IS NULL)
                  GROUP BY ss_store_sk
               )))
      )  v2
   )  v21
   WHERE (rnk < 11)
)  descending
, item i1
, item i2
WHERE (asceding.rnk = descending.rnk)
   AND (i1.i_item_sk = asceding.item_sk)
   AND (i2.i_item_sk = descending.item_sk)
ORDER BY asceding.rnk ASC,
   -- additional columns to assure results stability for larger scale factors; this is a deviation from TPC-DS specification
   i1.i_product_name ASC, i2.i_product_name ASC
LIMIT 100
"""

QUERIES["q49"] = """
SELECT
  'web' channel
, web.item
, web.return_ratio
, web.return_rank
, web.currency_rank
FROM
  (
   SELECT
     item
   , return_ratio
   , currency_ratio
   , rank() OVER (ORDER BY return_ratio ASC) return_rank
   , rank() OVER (ORDER BY currency_ratio ASC) currency_rank
   FROM
     (
      SELECT
        ws.ws_item_sk item
      , (CAST(sum(COALESCE(wr.wr_return_quantity, 0)) AS DECIMAL(15,4)) / CAST(sum(COALESCE(ws.ws_quantity, 0)) AS DECIMAL(15,4))) return_ratio
      , (CAST(sum(COALESCE(wr.wr_return_amt, 0)) AS DECIMAL(15,4)) / CAST(sum(COALESCE(ws.ws_net_paid, 0)) AS DECIMAL(15,4))) currency_ratio
      FROM
        (web_sales ws
      LEFT JOIN web_returns wr ON (ws.ws_order_number = wr.wr_order_number)
         AND (ws.ws_item_sk = wr.wr_item_sk))
      , date_dim
      WHERE (wr.wr_return_amt > 10000)
         AND (ws.ws_net_profit > 1)
         AND (ws.ws_net_paid > 0)
         AND (ws.ws_quantity > 0)
         AND (ws_sold_date_sk = d_date_sk)
         AND (d_year = 2001)
         AND (d_moy = 12)
      GROUP BY ws.ws_item_sk
   )  in_web
)  web
WHERE (web.return_rank <= 10)
   OR (web.currency_rank <= 10)
UNION SELECT
  'catalog' channel
, catalog.item
, catalog.return_ratio
, catalog.return_rank
, catalog.currency_rank
FROM
  (
   SELECT
     item
   , return_ratio
   , currency_ratio
   , rank() OVER (ORDER BY return_ratio ASC) return_rank
   , rank() OVER (ORDER BY currency_ratio ASC) currency_rank
   FROM
     (
      SELECT
        cs.cs_item_sk item
      , (CAST(sum(COALESCE(cr.cr_return_quantity, 0)) AS DECIMAL(15,4)) / CAST(sum(COALESCE(cs.cs_quantity, 0)) AS DECIMAL(15,4))) return_ratio
      , (CAST(sum(COALESCE(cr.cr_return_amount, 0)) AS DECIMAL(15,4)) / CAST(sum(COALESCE(cs.cs_net_paid, 0)) AS DECIMAL(15,4))) currency_ratio
      FROM
        (catalog_sales cs
      LEFT JOIN catalog_returns cr ON (cs.cs_order_number = cr.cr_order_number)
         AND (cs.cs_item_sk = cr.cr_item_sk))
      , date_dim
      WHERE (cr.cr_return_amount > 10000)
         AND (cs.cs_net_profit > 1)
         AND (cs.cs_net_paid > 0)
         AND (cs.cs_quantity > 0)
         AND (cs_sold_date_sk = d_date_sk)
         AND (d_year = 2001)
         AND (d_moy = 12)
      GROUP BY cs.cs_item_sk
   )  in_cat
)  CATALOG
WHERE (catalog.return_rank <= 10)
   OR (catalog.currency_rank <= 10)
UNION SELECT
  'store' channel
, store.item
, store.return_ratio
, store.return_rank
, store.currency_rank
FROM
  (
   SELECT
     item
   , return_ratio
   , currency_ratio
   , rank() OVER (ORDER BY return_ratio ASC) return_rank
   , rank() OVER (ORDER BY currency_ratio ASC) currency_rank
   FROM
     (
      SELECT
        sts.ss_item_sk item
      , (CAST(sum(COALESCE(sr.sr_return_quantity, 0)) AS DECIMAL(15,4)) / CAST(sum(COALESCE(sts.ss_quantity, 0)) AS DECIMAL(15,4))) return_ratio
      , (CAST(sum(COALESCE(sr.sr_return_amt, 0)) AS DECIMAL(15,4)) / CAST(sum(COALESCE(sts.ss_net_paid, 0)) AS DECIMAL(15,4))) currency_ratio
      FROM
        (store_sales sts
      LEFT JOIN store_returns sr ON (sts.ss_ticket_number = sr.sr_ticket_number)
         AND (sts.ss_item_sk = sr.sr_item_sk))
      , date_dim
      WHERE (sr.sr_return_amt > 10000)
         AND (sts.ss_net_profit > 1)
         AND (sts.ss_net_paid > 0)
         AND (sts.ss_quantity > 0)
         AND (ss_sold_date_sk = d_date_sk)
         AND (d_year = 2001)
         AND (d_moy = 12)
      GROUP BY sts.ss_item_sk
   )  in_store
)  store
WHERE (store.return_rank <= 10)
   OR (store.currency_rank <= 10)
ORDER BY 1 ASC, 4 ASC, 5 ASC, 2 ASC
LIMIT 100
"""

QUERIES["q50"] = """
SELECT
  s_store_name
, s_company_id
, s_street_number
, s_street_name
, s_street_type
, s_suite_number
, s_city
, s_county
, s_state
, s_zip
, sum((CASE WHEN ((sr_returned_date_sk - ss_sold_date_sk) <= 30) THEN 1 ELSE 0 END)) c_30_days
, sum((CASE WHEN ((sr_returned_date_sk - ss_sold_date_sk) > 30)
   AND ((sr_returned_date_sk - ss_sold_date_sk) <= 60) THEN 1 ELSE 0 END)) c_31_60_days
, sum((CASE WHEN ((sr_returned_date_sk - ss_sold_date_sk) > 60)
   AND ((sr_returned_date_sk - ss_sold_date_sk) <= 90) THEN 1 ELSE 0 END)) c_61_90_days
, sum((CASE WHEN ((sr_returned_date_sk - ss_sold_date_sk) > 90)
   AND ((sr_returned_date_sk - ss_sold_date_sk) <= 120) THEN 1 ELSE 0 END)) c_91_120_days
, sum((CASE WHEN ((sr_returned_date_sk - ss_sold_date_sk) > 120) THEN 1 ELSE 0 END)) c_120_days
FROM
  store_sales
, store_returns
, store
, date_dim d1
, date_dim d2
WHERE (d2.d_year = 2001)
   AND (d2.d_moy = 8)
   AND (ss_ticket_number = sr_ticket_number)
   AND (ss_item_sk = sr_item_sk)
   AND (ss_sold_date_sk = d1.d_date_sk)
   AND (sr_returned_date_sk = d2.d_date_sk)
   AND (ss_customer_sk = sr_customer_sk)
   AND (ss_store_sk = s_store_sk)
GROUP BY s_store_name, s_company_id, s_street_number, s_street_name, s_street_type, s_suite_number, s_city, s_county, s_state, s_zip
ORDER BY s_store_name ASC, s_company_id ASC, s_street_number ASC, s_street_name ASC, s_street_type ASC, s_suite_number ASC, s_city ASC, s_county ASC, s_state ASC, s_zip ASC
LIMIT 100
"""

QUERIES["q54"] = """
WITH
  my_customers AS (
   SELECT DISTINCT
     c_customer_sk
   , c_current_addr_sk
   FROM
     (
      SELECT
        cs_sold_date_sk sold_date_sk
      , cs_bill_customer_sk customer_sk
      , cs_item_sk item_sk
      FROM
        catalog_sales
UNION ALL       SELECT
        ws_sold_date_sk sold_date_sk
      , ws_bill_customer_sk customer_sk
      , ws_item_sk item_sk
      FROM
        web_sales
   )  cs_or_ws_sales
   , item
   , date_dim
   , customer
   WHERE (sold_date_sk = d_date_sk)
      AND (item_sk = i_item_sk)
      AND (i_category = 'Women')
      AND (i_class = 'maternity')
      AND (c_customer_sk = cs_or_ws_sales.customer_sk)
      AND (d_moy = 12)
      AND (d_year = 1998)
) 
, my_revenue AS (
   SELECT
     c_customer_sk
   , sum(ss_ext_sales_price) revenue
   FROM
     my_customers
   , store_sales
   , customer_address
   , store
   , date_dim
   WHERE (c_current_addr_sk = ca_address_sk)
      AND (ca_county = s_county)
      AND (ca_state = s_state)
      AND (ss_sold_date_sk = d_date_sk)
      AND (c_customer_sk = ss_customer_sk)
      AND (d_month_seq BETWEEN (
      SELECT DISTINCT (d_month_seq + 1)
      FROM
        date_dim
      WHERE (d_year = 1998)
         AND (d_moy = 12)
   ) AND (
      SELECT DISTINCT (d_month_seq + 3)
      FROM
        date_dim
      WHERE (d_year = 1998)
         AND (d_moy = 12)
   ))
   GROUP BY c_customer_sk
) 
, segments AS (
   SELECT CAST((revenue / 50) AS INTEGER) segment
   FROM
     my_revenue
) 
SELECT
  segment
, count(*) num_customers
, (segment * 50) segment_base
FROM
  segments
GROUP BY segment
ORDER BY segment ASC, num_customers ASC
LIMIT 100
"""

QUERIES["q56"] = """
WITH
  ss AS (
   SELECT
     i_item_id
   , sum(ss_ext_sales_price) total_sales
   FROM
     store_sales
   , date_dim
   , customer_address
   , item
   WHERE (i_item_id IN (
      SELECT i_item_id
      FROM
        item
      WHERE (i_color IN ('slate'      , 'blanched'      , 'burnished'))
   ))
      AND (ss_item_sk = i_item_sk)
      AND (ss_sold_date_sk = d_date_sk)
      AND (d_year = 2001)
      AND (d_moy = 2)
      AND (ss_addr_sk = ca_address_sk)
      AND (ca_gmt_offset = -5)
   GROUP BY i_item_id
) 
, cs AS (
   SELECT
     i_item_id
   , sum(cs_ext_sales_price) total_sales
   FROM
     catalog_sales
   , date_dim
   , customer_address
   , item
   WHERE (i_item_id IN (
      SELECT i_item_id
      FROM
        item
      WHERE (i_color IN ('slate'      , 'blanched'      , 'burnished'))
   ))
      AND (cs_item_sk = i_item_sk)
      AND (cs_sold_date_sk = d_date_sk)
      AND (d_year = 2001)
      AND (d_moy = 2)
      AND (cs_bill_addr_sk = ca_address_sk)
      AND (ca_gmt_offset = -5)
   GROUP BY i_item_id
) 
, ws AS (
   SELECT
     i_item_id
   , sum(ws_ext_sales_price) total_sales
   FROM
     web_sales
   , date_dim
   , customer_address
   , item
   WHERE (i_item_id IN (
      SELECT i_item_id
      FROM
        item
      WHERE (i_color IN ('slate'      , 'blanched'      , 'burnished'))
   ))
      AND (ws_item_sk = i_item_sk)
      AND (ws_sold_date_sk = d_date_sk)
      AND (d_year = 2001)
      AND (d_moy = 2)
      AND (ws_bill_addr_sk = ca_address_sk)
      AND (ca_gmt_offset = -5)
   GROUP BY i_item_id
) 
SELECT
  i_item_id
, sum(total_sales) total_sales
FROM
  (
   SELECT *
   FROM
     ss
UNION ALL    SELECT *
   FROM
     cs
UNION ALL    SELECT *
   FROM
     ws
)  tmp1
GROUP BY i_item_id
ORDER BY total_sales ASC, i_item_id ASC
LIMIT 100
"""

QUERIES["q58"] = """
WITH
  ss_items AS (
   SELECT
     i_item_id item_id
   , sum(ss_ext_sales_price) ss_item_rev
   FROM
     store_sales
   , item
   , date_dim
   WHERE (ss_item_sk = i_item_sk)
      AND (d_date IN (
      SELECT d_date
      FROM
        date_dim
      WHERE (d_week_seq = (
            SELECT d_week_seq
            FROM
              date_dim
            WHERE (d_date = CAST('2000-01-03' AS DATE))
         ))
   ))
      AND (ss_sold_date_sk = d_date_sk)
   GROUP BY i_item_id
) 
, cs_items AS (
   SELECT
     i_item_id item_id
   , sum(cs_ext_sales_price) cs_item_rev
   FROM
     catalog_sales
   , item
   , date_dim
   WHERE (cs_item_sk = i_item_sk)
      AND (d_date IN (
      SELECT d_date
      FROM
        date_dim
      WHERE (d_week_seq = (
            SELECT d_week_seq
            FROM
              date_dim
            WHERE (d_date = CAST('2000-01-03' AS DATE))
         ))
   ))
      AND (cs_sold_date_sk = d_date_sk)
   GROUP BY i_item_id
) 
, ws_items AS (
   SELECT
     i_item_id item_id
   , sum(ws_ext_sales_price) ws_item_rev
   FROM
     web_sales
   , item
   , date_dim
   WHERE (ws_item_sk = i_item_sk)
      AND (d_date IN (
      SELECT d_date
      FROM
        date_dim
      WHERE (d_week_seq = (
            SELECT d_week_seq
            FROM
              date_dim
            WHERE (d_date = CAST('2000-01-03' AS DATE))
         ))
   ))
      AND (ws_sold_date_sk = d_date_sk)
   GROUP BY i_item_id
) 
SELECT
  ss_items.item_id
, ss_item_rev
, CAST((((ss_item_rev / ((CAST(ss_item_rev AS DECIMAL(16,7)) + cs_item_rev) + ws_item_rev)) / 3) * 100) AS DECIMAL(7,2)) ss_dev
, cs_item_rev
, CAST((((cs_item_rev / ((CAST(ss_item_rev AS DECIMAL(16,7)) + cs_item_rev) + ws_item_rev)) / 3) * 100) AS DECIMAL(7,2)) cs_dev
, ws_item_rev
, CAST((((ws_item_rev / ((CAST(ss_item_rev AS DECIMAL(16,7)) + cs_item_rev) + ws_item_rev)) / 3) * 100) AS DECIMAL(7,2)) ws_dev
, (((ss_item_rev + cs_item_rev) + ws_item_rev) / 3) average
FROM
  ss_items
, cs_items
, ws_items
WHERE (ss_items.item_id = cs_items.item_id)
   AND (ss_items.item_id = ws_items.item_id)
   AND (ss_item_rev BETWEEN (0.9 * cs_item_rev) AND (1.1 * cs_item_rev))
   AND (ss_item_rev BETWEEN (0.9 * ws_item_rev) AND (1.1 * ws_item_rev))
   AND (cs_item_rev BETWEEN (0.9 * ss_item_rev) AND (1.1 * ss_item_rev))
   AND (cs_item_rev BETWEEN (0.9 * ws_item_rev) AND (1.1 * ws_item_rev))
   AND (ws_item_rev BETWEEN (0.9 * ss_item_rev) AND (1.1 * ss_item_rev))
   AND (ws_item_rev BETWEEN (0.9 * cs_item_rev) AND (1.1 * cs_item_rev))
ORDER BY ss_items.item_id ASC, ss_item_rev ASC
LIMIT 100
"""

QUERIES["q59"] = """
WITH
  wss AS (
   SELECT
     d_week_seq
   , ss_store_sk
   , sum((CASE WHEN (d_day_name = 'Sunday') THEN ss_sales_price ELSE null END)) sun_sales
   , sum((CASE WHEN (d_day_name = 'Monday') THEN ss_sales_price ELSE null END)) mon_sales
   , sum((CASE WHEN (d_day_name = 'Tuesday') THEN ss_sales_price ELSE null END)) tue_sales
   , sum((CASE WHEN (d_day_name = 'Wednesday') THEN ss_sales_price ELSE null END)) wed_sales
   , sum((CASE WHEN (d_day_name = 'Thursday') THEN ss_sales_price ELSE null END)) thu_sales
   , sum((CASE WHEN (d_day_name = 'Friday') THEN ss_sales_price ELSE null END)) fri_sales
   , sum((CASE WHEN (d_day_name = 'Saturday') THEN ss_sales_price ELSE null END)) sat_sales
   FROM
     store_sales
   , date_dim
   WHERE (d_date_sk = ss_sold_date_sk)
   GROUP BY d_week_seq, ss_store_sk
) 
SELECT
  s_store_name1
, s_store_id1
, d_week_seq1
, (sun_sales1 / sun_sales2)
, (mon_sales1 / mon_sales2)
, (tue_sales1 / tue_sales2)
, (wed_sales1 / wed_sales2)
, (thu_sales1 / thu_sales2)
, (fri_sales1 / fri_sales2)
, (sat_sales1 / sat_sales2)
FROM
  (
   SELECT
     s_store_name s_store_name1
   , wss.d_week_seq d_week_seq1
   , s_store_id s_store_id1
   , sun_sales sun_sales1
   , mon_sales mon_sales1
   , tue_sales tue_sales1
   , wed_sales wed_sales1
   , thu_sales thu_sales1
   , fri_sales fri_sales1
   , sat_sales sat_sales1
   FROM
     wss
   , store
   , date_dim d
   WHERE (d.d_week_seq = wss.d_week_seq)
      AND (ss_store_sk = s_store_sk)
      AND (d_month_seq BETWEEN 1212 AND (1212 + 11))
)  y
, (
   SELECT
     s_store_name s_store_name2
   , wss.d_week_seq d_week_seq2
   , s_store_id s_store_id2
   , sun_sales sun_sales2
   , mon_sales mon_sales2
   , tue_sales tue_sales2
   , wed_sales wed_sales2
   , thu_sales thu_sales2
   , fri_sales fri_sales2
   , sat_sales sat_sales2
   FROM
     wss
   , store
   , date_dim d
   WHERE (d.d_week_seq = wss.d_week_seq)
      AND (ss_store_sk = s_store_sk)
      AND (d_month_seq BETWEEN (1212 + 12) AND (1212 + 23))
)  x
WHERE (s_store_id1 = s_store_id2)
   AND (d_week_seq1 = (d_week_seq2 - 52))
ORDER BY s_store_name1 ASC, s_store_id1 ASC, d_week_seq1 ASC
LIMIT 100
"""

QUERIES["q60"] = """
WITH
  ss AS (
   SELECT
     i_item_id
   , sum(ss_ext_sales_price) total_sales
   FROM
     store_sales
   , date_dim
   , customer_address
   , item
   WHERE (i_item_id IN (
      SELECT i_item_id
      FROM
        item
      WHERE (i_category IN ('Music'))
   ))
      AND (ss_item_sk = i_item_sk)
      AND (ss_sold_date_sk = d_date_sk)
      AND (d_year = 1998)
      AND (d_moy = 9)
      AND (ss_addr_sk = ca_address_sk)
      AND (ca_gmt_offset = -5)
   GROUP BY i_item_id
) 
, cs AS (
   SELECT
     i_item_id
   , sum(cs_ext_sales_price) total_sales
   FROM
     catalog_sales
   , date_dim
   , customer_address
   , item
   WHERE (i_item_id IN (
      SELECT i_item_id
      FROM
        item
      WHERE (i_category IN ('Music'))
   ))
      AND (cs_item_sk = i_item_sk)
      AND (cs_sold_date_sk = d_date_sk)
      AND (d_year = 1998)
      AND (d_moy = 9)
      AND (cs_bill_addr_sk = ca_address_sk)
      AND (ca_gmt_offset = -5)
   GROUP BY i_item_id
) 
, ws AS (
   SELECT
     i_item_id
   , sum(ws_ext_sales_price) total_sales
   FROM
     web_sales
   , date_dim
   , customer_address
   , item
   WHERE (i_item_id IN (
      SELECT i_item_id
      FROM
        item
      WHERE (i_category IN ('Music'))
   ))
      AND (ws_item_sk = i_item_sk)
      AND (ws_sold_date_sk = d_date_sk)
      AND (d_year = 1998)
      AND (d_moy = 9)
      AND (ws_bill_addr_sk = ca_address_sk)
      AND (ca_gmt_offset = -5)
   GROUP BY i_item_id
) 
SELECT
  i_item_id
, sum(total_sales) total_sales
FROM
  (
   SELECT *
   FROM
     ss
UNION ALL    SELECT *
   FROM
     cs
UNION ALL    SELECT *
   FROM
     ws
)  tmp1
GROUP BY i_item_id
ORDER BY i_item_id ASC, total_sales ASC
LIMIT 100
"""

QUERIES["q61"] = """
SELECT
  promotions
, total
, ((CAST(promotions AS DECIMAL(15,4)) / CAST(total AS DECIMAL(15,4))) * 100)
FROM
  (
   SELECT sum(ss_ext_sales_price) promotions
   FROM
     store_sales
   , store
   , promotion
   , date_dim
   , customer
   , customer_address
   , item
   WHERE (ss_sold_date_sk = d_date_sk)
      AND (ss_store_sk = s_store_sk)
      AND (ss_promo_sk = p_promo_sk)
      AND (ss_customer_sk = c_customer_sk)
      AND (ca_address_sk = c_current_addr_sk)
      AND (ss_item_sk = i_item_sk)
      AND (ca_gmt_offset = -5)
      AND (i_category = 'Jewelry')
      AND ((p_channel_dmail = 'Y')
         OR (p_channel_email = 'Y')
         OR (p_channel_tv = 'Y'))
      AND (s_gmt_offset = -5)
      AND (d_year = 1998)
      AND (d_moy = 11)
)  promotional_sales
, (
   SELECT sum(ss_ext_sales_price) total
   FROM
     store_sales
   , store
   , date_dim
   , customer
   , customer_address
   , item
   WHERE (ss_sold_date_sk = d_date_sk)
      AND (ss_store_sk = s_store_sk)
      AND (ss_customer_sk = c_customer_sk)
      AND (ca_address_sk = c_current_addr_sk)
      AND (ss_item_sk = i_item_sk)
      AND (ca_gmt_offset = -5)
      AND (i_category = 'Jewelry')
      AND (s_gmt_offset = -5)
      AND (d_year = 1998)
      AND (d_moy = 11)
)  all_sales
ORDER BY promotions ASC, total ASC
LIMIT 100
"""

QUERIES["q64"] = """
WITH
  cs_ui AS (
   SELECT
     cs_item_sk
   , sum(cs_ext_list_price) sale
   , sum(((cr_refunded_cash + cr_reversed_charge) + cr_store_credit)) refund
   FROM
     catalog_sales
   , catalog_returns
   WHERE (cs_item_sk = cr_item_sk)
      AND (cs_order_number = cr_order_number)
   GROUP BY cs_item_sk
   HAVING (sum(cs_ext_list_price) > (2 * sum(((cr_refunded_cash + cr_reversed_charge) + cr_store_credit))))
) 
, cross_sales AS (
   SELECT
     i_product_name product_name
   , i_item_sk item_sk
   , s_store_name store_name
   , s_zip store_zip
   , ad1.ca_street_number b_street_number
   , ad1.ca_street_name b_street_name
   , ad1.ca_city b_city
   , ad1.ca_zip b_zip
   , ad2.ca_street_number c_street_number
   , ad2.ca_street_name c_street_name
   , ad2.ca_city c_city
   , ad2.ca_zip c_zip
   , d1.d_year syear
   , d2.d_year fsyear
   , d3.d_year s2year
   , count(*) cnt
   , sum(ss_wholesale_cost) s1
   , sum(ss_list_price) s2
   , sum(ss_coupon_amt) s3
   FROM
     store_sales
   , store_returns
   , cs_ui
   , date_dim d1
   , date_dim d2
   , date_dim d3
   , store
   , customer
   , customer_demographics cd1
   , customer_demographics cd2
   , promotion
   , household_demographics hd1
   , household_demographics hd2
   , customer_address ad1
   , customer_address ad2
   , income_band ib1
   , income_band ib2
   , item
   WHERE (ss_store_sk = s_store_sk)
      AND (ss_sold_date_sk = d1.d_date_sk)
      AND (ss_customer_sk = c_customer_sk)
      AND (ss_cdemo_sk = cd1.cd_demo_sk)
      AND (ss_hdemo_sk = hd1.hd_demo_sk)
      AND (ss_addr_sk = ad1.ca_address_sk)
      AND (ss_item_sk = i_item_sk)
      AND (ss_item_sk = sr_item_sk)
      AND (ss_ticket_number = sr_ticket_number)
      AND (ss_item_sk = cs_ui.cs_item_sk)
      AND (c_current_cdemo_sk = cd2.cd_demo_sk)
      AND (c_current_hdemo_sk = hd2.hd_demo_sk)
      AND (c_current_addr_sk = ad2.ca_address_sk)
      AND (c_first_sales_date_sk = d2.d_date_sk)
      AND (c_first_shipto_date_sk = d3.d_date_sk)
      AND (ss_promo_sk = p_promo_sk)
      AND (hd1.hd_income_band_sk = ib1.ib_income_band_sk)
      AND (hd2.hd_income_band_sk = ib2.ib_income_band_sk)
      AND (cd1.cd_marital_status <> cd2.cd_marital_status)
      AND (i_color IN ('purple'   , 'burlywood'   , 'indian'   , 'spring'   , 'floral'   , 'medium'))
      AND (i_current_price BETWEEN 64 AND (64 + 10))
      AND (i_current_price BETWEEN (64 + 1) AND (64 + 15))
   GROUP BY i_product_name, i_item_sk, s_store_name, s_zip, ad1.ca_street_number, ad1.ca_street_name, ad1.ca_city, ad1.ca_zip, ad2.ca_street_number, ad2.ca_street_name, ad2.ca_city, ad2.ca_zip, d1.d_year, d2.d_year, d3.d_year
) 
SELECT
  cs1.product_name
, cs1.store_name
, cs1.store_zip
, cs1.b_street_number
, cs1.b_street_name
, cs1.b_city
, cs1.b_zip
, cs1.c_street_number
, cs1.c_street_name
, cs1.c_city
, cs1.c_zip
, cs1.syear
, cs1.cnt
, cs1.s1 s11
, cs1.s2 s21
, cs1.s3 s31
, cs2.s1 s12
, cs2.s2 s22
, cs2.s3 s32
, cs2.syear
, cs2.cnt
FROM
  cross_sales cs1
, cross_sales cs2
WHERE (cs1.item_sk = cs2.item_sk)
   AND (cs1.syear = 1999)
   AND (cs2.syear = (1999 + 1))
   AND (cs2.cnt <= cs1.cnt)
   AND (cs1.store_name = cs2.store_name)
   AND (cs1.store_zip = cs2.store_zip)
ORDER BY cs1.product_name ASC, cs1.store_name ASC, cs2.cnt ASC, 14, 15, 16, 17, 18
"""

QUERIES["q66"] = """
SELECT
  w_warehouse_name
, w_warehouse_sq_ft
, w_city
, w_county
, w_state
, w_country
, ship_carriers
, year_
, sum(jan_sales) jan_sales
, sum(feb_sales) feb_sales
, sum(mar_sales) mar_sales
, sum(apr_sales) apr_sales
, sum(may_sales) may_sales
, sum(jun_sales) jun_sales
, sum(jul_sales) jul_sales
, sum(aug_sales) aug_sales
, sum(sep_sales) sep_sales
, sum(oct_sales) oct_sales
, sum(nov_sales) nov_sales
, sum(dec_sales) dec_sales
, sum((jan_sales / w_warehouse_sq_ft)) jan_sales_per_sq_foot
, sum((feb_sales / w_warehouse_sq_ft)) feb_sales_per_sq_foot
, sum((mar_sales / w_warehouse_sq_ft)) mar_sales_per_sq_foot
, sum((apr_sales / w_warehouse_sq_ft)) apr_sales_per_sq_foot
, sum((may_sales / w_warehouse_sq_ft)) may_sales_per_sq_foot
, sum((jun_sales / w_warehouse_sq_ft)) jun_sales_per_sq_foot
, sum((jul_sales / w_warehouse_sq_ft)) jul_sales_per_sq_foot
, sum((aug_sales / w_warehouse_sq_ft)) aug_sales_per_sq_foot
, sum((sep_sales / w_warehouse_sq_ft)) sep_sales_per_sq_foot
, sum((oct_sales / w_warehouse_sq_ft)) oct_sales_per_sq_foot
, sum((nov_sales / w_warehouse_sq_ft)) nov_sales_per_sq_foot
, sum((dec_sales / w_warehouse_sq_ft)) dec_sales_per_sq_foot
, sum(jan_net) jan_net
, sum(feb_net) feb_net
, sum(mar_net) mar_net
, sum(apr_net) apr_net
, sum(may_net) may_net
, sum(jun_net) jun_net
, sum(jul_net) jul_net
, sum(aug_net) aug_net
, sum(sep_net) sep_net
, sum(oct_net) oct_net
, sum(nov_net) nov_net
, sum(dec_net) dec_net
FROM
(
      SELECT
        w_warehouse_name
      , w_warehouse_sq_ft
      , w_city
      , w_county
      , w_state
      , w_country
      , concat(concat('DHL', ','), 'BARIAN') ship_carriers
      , d_year year_
      , sum((CASE WHEN (d_moy = 1) THEN (ws_ext_sales_price * ws_quantity) ELSE 0 END)) jan_sales
      , sum((CASE WHEN (d_moy = 2) THEN (ws_ext_sales_price * ws_quantity) ELSE 0 END)) feb_sales
      , sum((CASE WHEN (d_moy = 3) THEN (ws_ext_sales_price * ws_quantity) ELSE 0 END)) mar_sales
      , sum((CASE WHEN (d_moy = 4) THEN (ws_ext_sales_price * ws_quantity) ELSE 0 END)) apr_sales
      , sum((CASE WHEN (d_moy = 5) THEN (ws_ext_sales_price * ws_quantity) ELSE 0 END)) may_sales
      , sum((CASE WHEN (d_moy = 6) THEN (ws_ext_sales_price * ws_quantity) ELSE 0 END)) jun_sales
      , sum((CASE WHEN (d_moy = 7) THEN (ws_ext_sales_price * ws_quantity) ELSE 0 END)) jul_sales
      , sum((CASE WHEN (d_moy = 8) THEN (ws_ext_sales_price * ws_quantity) ELSE 0 END)) aug_sales
      , sum((CASE WHEN (d_moy = 9) THEN (ws_ext_sales_price * ws_quantity) ELSE 0 END)) sep_sales
      , sum((CASE WHEN (d_moy = 10) THEN (ws_ext_sales_price * ws_quantity) ELSE 0 END)) oct_sales
      , sum((CASE WHEN (d_moy = 11) THEN (ws_ext_sales_price * ws_quantity) ELSE 0 END)) nov_sales
      , sum((CASE WHEN (d_moy = 12) THEN (ws_ext_sales_price * ws_quantity) ELSE 0 END)) dec_sales
      , sum((CASE WHEN (d_moy = 1) THEN (ws_net_paid * ws_quantity) ELSE 0 END)) jan_net
      , sum((CASE WHEN (d_moy = 2) THEN (ws_net_paid * ws_quantity) ELSE 0 END)) feb_net
      , sum((CASE WHEN (d_moy = 3) THEN (ws_net_paid * ws_quantity) ELSE 0 END)) mar_net
      , sum((CASE WHEN (d_moy = 4) THEN (ws_net_paid * ws_quantity) ELSE 0 END)) apr_net
      , sum((CASE WHEN (d_moy = 5) THEN (ws_net_paid * ws_quantity) ELSE 0 END)) may_net
      , sum((CASE WHEN (d_moy = 6) THEN (ws_net_paid * ws_quantity) ELSE 0 END)) jun_net
      , sum((CASE WHEN (d_moy = 7) THEN (ws_net_paid * ws_quantity) ELSE 0 END)) jul_net
      , sum((CASE WHEN (d_moy = 8) THEN (ws_net_paid * ws_quantity) ELSE 0 END)) aug_net
      , sum((CASE WHEN (d_moy = 9) THEN (ws_net_paid * ws_quantity) ELSE 0 END)) sep_net
      , sum((CASE WHEN (d_moy = 10) THEN (ws_net_paid * ws_quantity) ELSE 0 END)) oct_net
      , sum((CASE WHEN (d_moy = 11) THEN (ws_net_paid * ws_quantity) ELSE 0 END)) nov_net
      , sum((CASE WHEN (d_moy = 12) THEN (ws_net_paid * ws_quantity) ELSE 0 END)) dec_net
      FROM
        web_sales
      , warehouse
      , date_dim
      , time_dim
      , ship_mode
      WHERE (ws_warehouse_sk = w_warehouse_sk)
         AND (ws_sold_date_sk = d_date_sk)
         AND (ws_sold_time_sk = t_time_sk)
         AND (ws_ship_mode_sk = sm_ship_mode_sk)
         AND (d_year = 2001)
         AND (t_time BETWEEN 30838 AND (30838 + 28800))
         AND (sm_carrier IN ('DHL'      , 'BARIAN'))
      GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state, w_country, d_year
   UNION ALL
      SELECT
        w_warehouse_name
      , w_warehouse_sq_ft
      , w_city
      , w_county
      , w_state
      , w_country
      , concat(concat('DHL', ','), 'BARIAN') ship_carriers
      , d_year year_
      , sum((CASE WHEN (d_moy = 1) THEN (cs_sales_price * cs_quantity) ELSE 0 END)) jan_sales
      , sum((CASE WHEN (d_moy = 2) THEN (cs_sales_price * cs_quantity) ELSE 0 END)) feb_sales
      , sum((CASE WHEN (d_moy = 3) THEN (cs_sales_price * cs_quantity) ELSE 0 END)) mar_sales
      , sum((CASE WHEN (d_moy = 4) THEN (cs_sales_price * cs_quantity) ELSE 0 END)) apr_sales
      , sum((CASE WHEN (d_moy = 5) THEN (cs_sales_price * cs_quantity) ELSE 0 END)) may_sales
      , sum((CASE WHEN (d_moy = 6) THEN (cs_sales_price * cs_quantity) ELSE 0 END)) jun_sales
      , sum((CASE WHEN (d_moy = 7) THEN (cs_sales_price * cs_quantity) ELSE 0 END)) jul_sales
      , sum((CASE WHEN (d_moy = 8) THEN (cs_sales_price * cs_quantity) ELSE 0 END)) aug_sales
      , sum((CASE WHEN (d_moy = 9) THEN (cs_sales_price * cs_quantity) ELSE 0 END)) sep_sales
      , sum((CASE WHEN (d_moy = 10) THEN (cs_sales_price * cs_quantity) ELSE 0 END)) oct_sales
      , sum((CASE WHEN (d_moy = 11) THEN (cs_sales_price * cs_quantity) ELSE 0 END)) nov_sales
      , sum((CASE WHEN (d_moy = 12) THEN (cs_sales_price * cs_quantity) ELSE 0 END)) dec_sales
      , sum((CASE WHEN (d_moy = 1) THEN (cs_net_paid_inc_tax * cs_quantity) ELSE 0 END)) jan_net
      , sum((CASE WHEN (d_moy = 2) THEN (cs_net_paid_inc_tax * cs_quantity) ELSE 0 END)) feb_net
      , sum((CASE WHEN (d_moy = 3) THEN (cs_net_paid_inc_tax * cs_quantity) ELSE 0 END)) mar_net
      , sum((CASE WHEN (d_moy = 4) THEN (cs_net_paid_inc_tax * cs_quantity) ELSE 0 END)) apr_net
      , sum((CASE WHEN (d_moy = 5) THEN (cs_net_paid_inc_tax * cs_quantity) ELSE 0 END)) may_net
      , sum((CASE WHEN (d_moy = 6) THEN (cs_net_paid_inc_tax * cs_quantity) ELSE 0 END)) jun_net
      , sum((CASE WHEN (d_moy = 7) THEN (cs_net_paid_inc_tax * cs_quantity) ELSE 0 END)) jul_net
      , sum((CASE WHEN (d_moy = 8) THEN (cs_net_paid_inc_tax * cs_quantity) ELSE 0 END)) aug_net
      , sum((CASE WHEN (d_moy = 9) THEN (cs_net_paid_inc_tax * cs_quantity) ELSE 0 END)) sep_net
      , sum((CASE WHEN (d_moy = 10) THEN (cs_net_paid_inc_tax * cs_quantity) ELSE 0 END)) oct_net
      , sum((CASE WHEN (d_moy = 11) THEN (cs_net_paid_inc_tax * cs_quantity) ELSE 0 END)) nov_net
      , sum((CASE WHEN (d_moy = 12) THEN (cs_net_paid_inc_tax * cs_quantity) ELSE 0 END)) dec_net
      FROM
        catalog_sales
      , warehouse
      , date_dim
      , time_dim
      , ship_mode
      WHERE (cs_warehouse_sk = w_warehouse_sk)
         AND (cs_sold_date_sk = d_date_sk)
         AND (cs_sold_time_sk = t_time_sk)
         AND (cs_ship_mode_sk = sm_ship_mode_sk)
         AND (d_year = 2001)
         AND (t_time BETWEEN 30838 AND (30838 + 28800))
         AND (sm_carrier IN ('DHL'      , 'BARIAN'))
      GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state, w_country, d_year
   )  x
GROUP BY w_warehouse_name, w_warehouse_sq_ft, w_city, w_county, w_state, w_country, ship_carriers, year_
ORDER BY w_warehouse_name ASC
LIMIT 100
"""

QUERIES["q69"] = """
SELECT
  cd_gender
, cd_marital_status
, cd_education_status
, count(*) cnt1
, cd_purchase_estimate
, count(*) cnt2
, cd_credit_rating
, count(*) cnt3
FROM
  customer c
, customer_address ca
, customer_demographics
WHERE (c.c_current_addr_sk = ca.ca_address_sk)
   AND (ca_state IN ('KY', 'GA', 'NM'))
   AND (cd_demo_sk = c.c_current_cdemo_sk)
   AND (EXISTS (
   SELECT *
   FROM
     store_sales
   , date_dim
   WHERE (c.c_customer_sk = ss_customer_sk)
      AND (ss_sold_date_sk = d_date_sk)
      AND (d_year = 2001)
      AND (d_moy BETWEEN 4 AND (4 + 2))
))
   AND (NOT (EXISTS (
   SELECT *
   FROM
     web_sales
   , date_dim
   WHERE (c.c_customer_sk = ws_bill_customer_sk)
      AND (ws_sold_date_sk = d_date_sk)
      AND (d_year = 2001)
      AND (d_moy BETWEEN 4 AND (4 + 2))
)))
   AND (NOT (EXISTS (
   SELECT *
   FROM
     catalog_sales
   , date_dim
   WHERE (c.c_customer_sk = cs_ship_customer_sk)
      AND (cs_sold_date_sk = d_date_sk)
      AND (d_year = 2001)
      AND (d_moy BETWEEN 4 AND (4 + 2))
)))
GROUP BY cd_gender, cd_marital_status, cd_education_status, cd_purchase_estimate, cd_credit_rating
ORDER BY cd_gender ASC, cd_marital_status ASC, cd_education_status ASC, cd_purchase_estimate ASC, cd_credit_rating ASC
LIMIT 100
"""

QUERIES["q71"] = """
SELECT
  i_brand_id brand_id
, i_brand brand
, t_hour
, t_minute
, sum(ext_price) ext_price
FROM
  item
, (
   SELECT
     ws_ext_sales_price ext_price
   , ws_sold_date_sk sold_date_sk
   , ws_item_sk sold_item_sk
   , ws_sold_time_sk time_sk
   FROM
     web_sales
   , date_dim
   WHERE (d_date_sk = ws_sold_date_sk)
      AND (d_moy = 11)
      AND (d_year = 1999)
UNION ALL    SELECT
     cs_ext_sales_price ext_price
   , cs_sold_date_sk sold_date_sk
   , cs_item_sk sold_item_sk
   , cs_sold_time_sk time_sk
   FROM
     catalog_sales
   , date_dim
   WHERE (d_date_sk = cs_sold_date_sk)
      AND (d_moy = 11)
      AND (d_year = 1999)
UNION ALL    SELECT
     ss_ext_sales_price ext_price
   , ss_sold_date_sk sold_date_sk
   , ss_item_sk sold_item_sk
   , ss_sold_time_sk time_sk
   FROM
     store_sales
   , date_dim
   WHERE (d_date_sk = ss_sold_date_sk)
      AND (d_moy = 11)
      AND (d_year = 1999)
)  tmp
, time_dim
WHERE (sold_item_sk = i_item_sk)
   AND (i_manager_id = 1)
   AND (time_sk = t_time_sk)
   AND ((t_meal_time = 'breakfast')
      OR (t_meal_time = 'dinner'))
GROUP BY i_brand, i_brand_id, t_hour, t_minute
ORDER BY ext_price DESC, i_brand_id ASC,
   -- additional columns to assure results stability for larger scale factors; this is a deviation from TPC-DS specification
   t_hour ASC, t_minute ASC
"""

QUERIES["q76"] = """
SELECT
  channel
, col_name
, d_year
, d_qoy
, i_category
, count(*) sales_cnt
, sum(ext_sales_price) sales_amt
FROM
  (
   SELECT
     'store' channel
   , 'ss_store_sk' col_name
   , d_year
   , d_qoy
   , i_category
   , ss_ext_sales_price ext_sales_price
   FROM
     store_sales
   , item
   , date_dim
   WHERE (ss_store_sk IS NULL)
      AND (ss_sold_date_sk = d_date_sk)
      AND (ss_item_sk = i_item_sk)
UNION ALL    SELECT
     'web' channel
   , 'ws_ship_customer_sk' col_name
   , d_year
   , d_qoy
   , i_category
   , ws_ext_sales_price ext_sales_price
   FROM
     web_sales
   , item
   , date_dim
   WHERE (ws_ship_customer_sk IS NULL)
      AND (ws_sold_date_sk = d_date_sk)
      AND (ws_item_sk = i_item_sk)
UNION ALL    SELECT
     'catalog' channel
   , 'cs_ship_addr_sk' col_name
   , d_year
   , d_qoy
   , i_category
   , cs_ext_sales_price ext_sales_price
   FROM
     catalog_sales
   , item
   , date_dim
   WHERE (cs_ship_addr_sk IS NULL)
      AND (cs_sold_date_sk = d_date_sk)
      AND (cs_item_sk = i_item_sk)
)  foo
GROUP BY channel, col_name, d_year, d_qoy, i_category
ORDER BY channel ASC, col_name ASC, d_year ASC, d_qoy ASC, i_category ASC
LIMIT 100
"""

QUERIES["q81"] = """
WITH
  customer_total_return AS (
   SELECT
     cr_returning_customer_sk ctr_customer_sk
   , ca_state ctr_state
   , sum(cr_return_amt_inc_tax) ctr_total_return
   FROM
     catalog_returns
   , date_dim
   , customer_address
   WHERE (cr_returned_date_sk = d_date_sk)
      AND (d_year = 2000)
      AND (cr_returning_addr_sk = ca_address_sk)
   GROUP BY cr_returning_customer_sk, ca_state
) 
SELECT
  c_customer_id
, c_salutation
, c_first_name
, c_last_name
, ca_street_number
, ca_street_name
, ca_street_type
, ca_suite_number
, ca_city
, ca_county
, ca_state
, ca_zip
, ca_country
, ca_gmt_offset
, ca_location_type
, ctr_total_return
FROM
  customer_total_return ctr1
, customer_address
, customer
WHERE (ctr1.ctr_total_return > (
      SELECT (avg(ctr_total_return) * 1.2)
      FROM
        customer_total_return ctr2
      WHERE (ctr1.ctr_state = ctr2.ctr_state)
   ))
   AND (ca_address_sk = c_current_addr_sk)
   AND (ca_state = 'GA')
   AND (ctr1.ctr_customer_sk = c_customer_sk)
ORDER BY c_customer_id ASC, c_salutation ASC, c_first_name ASC, c_last_name ASC, ca_street_number ASC, ca_street_name ASC, ca_street_type ASC, ca_suite_number ASC, ca_city ASC, ca_county ASC, ca_state ASC, ca_zip ASC, ca_country ASC, ca_gmt_offset ASC, ca_location_type ASC, ctr_total_return ASC
LIMIT 100
"""

QUERIES["q83"] = """
WITH
  sr_items AS (
   SELECT
     i_item_id item_id
   , sum(sr_return_quantity) sr_item_qty
   FROM
     store_returns
   , item
   , date_dim
   WHERE (sr_item_sk = i_item_sk)
      AND (d_date IN (
      SELECT d_date
      FROM
        date_dim
      WHERE (d_week_seq IN (
         SELECT d_week_seq
         FROM
           date_dim
         WHERE (d_date IN (CAST('2000-06-30' AS DATE)         , CAST('2000-09-27' AS DATE)         , CAST('2000-11-17' AS DATE)))
      ))
   ))
      AND (sr_returned_date_sk = d_date_sk)
   GROUP BY i_item_id
) 
, cr_items AS (
   SELECT
     i_item_id item_id
   , sum(cr_return_quantity) cr_item_qty
   FROM
     catalog_returns
   , item
   , date_dim
   WHERE (cr_item_sk = i_item_sk)
      AND (d_date IN (
      SELECT d_date
      FROM
        date_dim
      WHERE (d_week_seq IN (
         SELECT d_week_seq
         FROM
           date_dim
         WHERE (d_date IN (CAST('2000-06-30' AS DATE)         , CAST('2000-09-27' AS DATE)         , CAST('2000-11-17' AS DATE)))
      ))
   ))
      AND (cr_returned_date_sk = d_date_sk)
   GROUP BY i_item_id
) 
, wr_items AS (
   SELECT
     i_item_id item_id
   , sum(wr_return_quantity) wr_item_qty
   FROM
     web_returns
   , item
   , date_dim
   WHERE (wr_item_sk = i_item_sk)
      AND (d_date IN (
      SELECT d_date
      FROM
        date_dim
      WHERE (d_week_seq IN (
         SELECT d_week_seq
         FROM
           date_dim
         WHERE (d_date IN (CAST('2000-06-30' AS DATE)         , CAST('2000-09-27' AS DATE)         , CAST('2000-11-17' AS DATE)))
      ))
   ))
      AND (wr_returned_date_sk = d_date_sk)
   GROUP BY i_item_id
) 
SELECT
  sr_items.item_id
, sr_item_qty
, CAST((((sr_item_qty / ((CAST(sr_item_qty AS DECIMAL(9,4)) + cr_item_qty) + wr_item_qty)) / 3.0) * 100) AS DECIMAL(7,2)) sr_dev
, cr_item_qty
, CAST((((cr_item_qty / ((CAST(sr_item_qty AS DECIMAL(9,4)) + cr_item_qty) + wr_item_qty)) / 3.0) * 100) AS DECIMAL(7,2)) cr_dev
, wr_item_qty
, CAST((((wr_item_qty / ((CAST(sr_item_qty AS DECIMAL(9,4)) + cr_item_qty) + wr_item_qty)) / 3.0) * 100) AS DECIMAL(7,2)) wr_dev
, (((sr_item_qty + cr_item_qty) + wr_item_qty) / 3.00) average
FROM
  sr_items
, cr_items
, wr_items
WHERE (sr_items.item_id = cr_items.item_id)
   AND (sr_items.item_id = wr_items.item_id)
ORDER BY sr_items.item_id ASC, sr_item_qty ASC
LIMIT 100
"""

QUERIES["q85"] = """
SELECT
  substr(r_reason_desc, 1, 20)
, avg(ws_quantity)
, avg(wr_refunded_cash)
, avg(wr_fee)
FROM
  web_sales
, web_returns
, web_page
, customer_demographics cd1
, customer_demographics cd2
, customer_address
, date_dim
, reason
WHERE (ws_web_page_sk = wp_web_page_sk)
   AND (ws_item_sk = wr_item_sk)
   AND (ws_order_number = wr_order_number)
   AND (ws_sold_date_sk = d_date_sk)
   AND (d_year = 2000)
   AND (cd1.cd_demo_sk = wr_refunded_cdemo_sk)
   AND (cd2.cd_demo_sk = wr_returning_cdemo_sk)
   AND (ca_address_sk = wr_refunded_addr_sk)
   AND (r_reason_sk = wr_reason_sk)
   AND (((cd1.cd_marital_status = 'M')
         AND (cd1.cd_marital_status = cd2.cd_marital_status)
         AND (cd1.cd_education_status = 'Advanced Degree')
         AND (cd1.cd_education_status = cd2.cd_education_status)
         AND (ws_sales_price BETWEEN 100.00 AND 150.00))
      OR ((cd1.cd_marital_status = 'S')
         AND (cd1.cd_marital_status = cd2.cd_marital_status)
         AND (cd1.cd_education_status = 'College')
         AND (cd1.cd_education_status = cd2.cd_education_status)
         AND (ws_sales_price BETWEEN 50.00 AND 100.00))
      OR ((cd1.cd_marital_status = 'W')
         AND (cd1.cd_marital_status = cd2.cd_marital_status)
         AND (cd1.cd_education_status = '2 yr Degree')
         AND (cd1.cd_education_status = cd2.cd_education_status)
         AND (ws_sales_price BETWEEN 150.00 AND 200.00)))
   AND (((ca_country = 'United States')
         AND (ca_state IN ('IN'      , 'OH'      , 'NJ'))
         AND (ws_net_profit BETWEEN 100 AND 200))
      OR ((ca_country = 'United States')
         AND (ca_state IN ('WI'      , 'CT'      , 'KY'))
         AND (ws_net_profit BETWEEN 150 AND 300))
      OR ((ca_country = 'United States')
         AND (ca_state IN ('LA'      , 'IA'      , 'AR'))
         AND (ws_net_profit BETWEEN 50 AND 250)))
GROUP BY r_reason_desc
ORDER BY substr(r_reason_desc, 1, 20) ASC, avg(ws_quantity) ASC, avg(wr_refunded_cash) ASC, avg(wr_fee) ASC
LIMIT 100
"""

QUERIES["q90"] = """
SELECT (CAST(amc AS DECIMAL(15,4)) / CAST(pmc AS DECIMAL(15,4))) am_pm_ratio
FROM
  (
   SELECT count(*) amc
   FROM
     web_sales
   , household_demographics
   , time_dim
   , web_page
   WHERE (ws_sold_time_sk = time_dim.t_time_sk)
      AND (ws_ship_hdemo_sk = household_demographics.hd_demo_sk)
      AND (ws_web_page_sk = web_page.wp_web_page_sk)
      AND (time_dim.t_hour BETWEEN 8 AND (8 + 1))
      AND (household_demographics.hd_dep_count = 6)
      AND (web_page.wp_char_count BETWEEN 5000 AND 5200)
)  at
, (
   SELECT count(*) pmc
   FROM
     web_sales
   , household_demographics
   , time_dim
   , web_page
   WHERE (ws_sold_time_sk = time_dim.t_time_sk)
      AND (ws_ship_hdemo_sk = household_demographics.hd_demo_sk)
      AND (ws_web_page_sk = web_page.wp_web_page_sk)
      AND (time_dim.t_hour BETWEEN 19 AND (19 + 1))
      AND (household_demographics.hd_dep_count = 6)
      AND (web_page.wp_char_count BETWEEN 5000 AND 5200)
)  pt
ORDER BY am_pm_ratio ASC
LIMIT 100
"""

QUERIES["q91"] = """
SELECT
  cc_call_center_id Call_Center
, cc_name Call_Center_Name
, cc_manager Manager
, sum(cr_net_loss) Returns_Loss
FROM
  call_center
, catalog_returns
, date_dim
, customer
, customer_address
, customer_demographics
, household_demographics
WHERE (cr_call_center_sk = cc_call_center_sk)
   AND (cr_returned_date_sk = d_date_sk)
   AND (cr_returning_customer_sk = c_customer_sk)
   AND (cd_demo_sk = c_current_cdemo_sk)
   AND (hd_demo_sk = c_current_hdemo_sk)
   AND (ca_address_sk = c_current_addr_sk)
   AND (d_year = 1998)
   AND (d_moy = 11)
   AND (((cd_marital_status = 'M')
         AND (cd_education_status = 'Unknown'))
      OR ((cd_marital_status = 'W')
         AND (cd_education_status = 'Advanced Degree')))
   AND (hd_buy_potential LIKE 'Unknown%')
   AND (ca_gmt_offset = -7)
GROUP BY cc_call_center_id, cc_name, cc_manager, cd_marital_status, cd_education_status
ORDER BY sum(cr_net_loss) DESC
"""

QUERIES["q92"] = """
SELECT sum(ws_ext_discount_amt) Excess_Discount_Amount
FROM
  web_sales
, item
, date_dim
WHERE (i_manufact_id = 350)
   AND (i_item_sk = ws_item_sk)
   AND (d_date BETWEEN CAST('2000-01-27' AS DATE) AND (CAST('2000-01-27' AS DATE) + INTERVAL  '90' DAY))
   AND (d_date_sk = ws_sold_date_sk)
   AND (ws_ext_discount_amt > (
      SELECT (1.3 * avg(ws_ext_discount_amt))
      FROM
        web_sales
      , date_dim
      WHERE (ws_item_sk = i_item_sk)
         AND (d_date BETWEEN CAST('2000-01-27' AS DATE) AND (CAST('2000-01-27' AS DATE) + INTERVAL  '90' DAY))
         AND (d_date_sk = ws_sold_date_sk)
   ))
ORDER BY sum(ws_ext_discount_amt) ASC
LIMIT 100
"""

QUERIES["q94"] = """
SELECT
  count(DISTINCT ws_order_number) order_count
, sum(ws_ext_ship_cost) total_shipping_cost
, sum(ws_net_profit) total_net_profit
FROM
  web_sales ws1
, date_dim
, customer_address
, web_site
WHERE (d_date BETWEEN CAST('1999-2-01' AS DATE) AND (CAST('1999-2-01' AS DATE) + INTERVAL  '60' DAY))
   AND (ws1.ws_ship_date_sk = d_date_sk)
   AND (ws1.ws_ship_addr_sk = ca_address_sk)
   AND (ca_state = 'IL')
   AND (ws1.ws_web_site_sk = web_site_sk)
   AND (web_company_name = 'pri')
   AND (EXISTS (
   SELECT *
   FROM
     web_sales ws2
   WHERE (ws1.ws_order_number = ws2.ws_order_number)
      AND (ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
))
   AND (NOT (EXISTS (
   SELECT *
   FROM
     web_returns wr1
   WHERE (ws1.ws_order_number = wr1.wr_order_number)
)))
ORDER BY count(DISTINCT ws_order_number) ASC
LIMIT 100
"""

# q47: moving average restated in DOUBLE and the sort key
# rounded with full-column tie-breaks so the LIMIT boundary is
# deterministic across engines (decimal-avg scale rounding vs
# float would otherwise flip near-tie orderings)
QUERIES["q47"] = """
WITH
  v1 AS (
   SELECT
     i_category
   , i_brand
   , s_store_name
   , s_company_name
   , d_year
   , d_moy
   , sum(ss_sales_price) sum_sales
   , avg(cast(sum(ss_sales_price) as double)) OVER (PARTITION BY i_category, i_brand, s_store_name, s_company_name, d_year) avg_monthly_sales
   , rank() OVER (PARTITION BY i_category, i_brand, s_store_name, s_company_name ORDER BY d_year ASC, d_moy ASC) rn
   FROM
     item
   , store_sales
   , date_dim
   , store
   WHERE (ss_item_sk = i_item_sk)
      AND (ss_sold_date_sk = d_date_sk)
      AND (ss_store_sk = s_store_sk)
      AND ((d_year = 1999)
         OR ((d_year = (1999 - 1))
            AND (d_moy = 12))
         OR ((d_year = (1999 + 1))
            AND (d_moy = 1)))
   GROUP BY i_category, i_brand, s_store_name, s_company_name, d_year, d_moy
) 
, v2 AS (
   SELECT
     v1.i_category
   , v1.i_brand
   , v1.s_store_name
   , v1.s_company_name
   , v1.d_year
   , v1.d_moy
   , v1.avg_monthly_sales
   , v1.sum_sales
   , v1_lag.sum_sales psum
   , v1_lead.sum_sales nsum
   FROM
     v1
   , v1 v1_lag
   , v1 v1_lead
   WHERE (v1.i_category = v1_lag.i_category)
      AND (v1.i_category = v1_lead.i_category)
      AND (v1.i_brand = v1_lag.i_brand)
      AND (v1.i_brand = v1_lead.i_brand)
      AND (v1.s_store_name = v1_lag.s_store_name)
      AND (v1.s_store_name = v1_lead.s_store_name)
      AND (v1.s_company_name = v1_lag.s_company_name)
      AND (v1.s_company_name = v1_lead.s_company_name)
      AND (v1.rn = (v1_lag.rn + 1))
      AND (v1.rn = (v1_lead.rn - 1))
) 
SELECT *
FROM
  v2
WHERE (d_year = 1999)
   AND (avg_monthly_sales > 0)
   AND ((CASE WHEN (avg_monthly_sales > 0) THEN (abs((sum_sales - avg_monthly_sales)) / avg_monthly_sales) ELSE null END) > 0.1)
ORDER BY round(sum_sales - avg_monthly_sales, 1) ASC, 1 asc, 2 asc, 3 asc, 4 asc, 5 asc, 6 asc, 7 asc, 8 asc, 9 asc, 10 asc
LIMIT 100
"""

# q57: moving average restated in DOUBLE and the sort key
# rounded with full-column tie-breaks so the LIMIT boundary is
# deterministic across engines (decimal-avg scale rounding vs
# float would otherwise flip near-tie orderings)
QUERIES["q57"] = """
WITH
  v1 AS (
   SELECT
     i_category
   , i_brand
   , cc_name
   , d_year
   , d_moy
   , sum(cs_sales_price) sum_sales
   , avg(cast(sum(cs_sales_price) as double)) OVER (PARTITION BY i_category, i_brand, cc_name, d_year) avg_monthly_sales
   , rank() OVER (PARTITION BY i_category, i_brand, cc_name ORDER BY d_year ASC, d_moy ASC) rn
   FROM
     item
   , catalog_sales
   , date_dim
   , call_center
   WHERE (cs_item_sk = i_item_sk)
      AND (cs_sold_date_sk = d_date_sk)
      AND (cc_call_center_sk = cs_call_center_sk)
      AND ((d_year = 1999)
         OR ((d_year = (1999 - 1))
            AND (d_moy = 12))
         OR ((d_year = (1999 + 1))
            AND (d_moy = 1)))
   GROUP BY i_category, i_brand, cc_name, d_year, d_moy
) 
, v2 AS (
   SELECT
     v1.i_category
   , v1.i_brand
   , v1.cc_name
   , v1.d_year
   , v1.d_moy
   , v1.avg_monthly_sales
   , v1.sum_sales
   , v1_lag.sum_sales psum
   , v1_lead.sum_sales nsum
   FROM
     v1
   , v1 v1_lag
   , v1 v1_lead
   WHERE (v1.i_category = v1_lag.i_category)
      AND (v1.i_category = v1_lead.i_category)
      AND (v1.i_brand = v1_lag.i_brand)
      AND (v1.i_brand = v1_lead.i_brand)
      AND (v1.cc_name = v1_lag.cc_name)
      AND (v1.cc_name = v1_lead.cc_name)
      AND (v1.rn = (v1_lag.rn + 1))
      AND (v1.rn = (v1_lead.rn - 1))
) 
SELECT *
FROM
  v2
WHERE (d_year = 1999)
   AND (avg_monthly_sales > 0)
   AND ((CASE WHEN (avg_monthly_sales > 0) THEN (abs((sum_sales - avg_monthly_sales)) / avg_monthly_sales) ELSE null END) > 0.1)
ORDER BY round(sum_sales - avg_monthly_sales, 1) ASC, 1 asc, 2 asc, 3 asc, 4 asc, 5 asc, 6 asc, 7 asc, 8 asc, 9 asc
LIMIT 100
"""

QUERIES["q51"] = """
WITH
  web_v1 AS (
   SELECT
     ws_item_sk item_sk
   , d_date
   , sum(sum(ws_sales_price)) OVER (PARTITION BY ws_item_sk ORDER BY d_date ASC ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) cume_sales
   FROM
     web_sales
   , date_dim
   WHERE (ws_sold_date_sk = d_date_sk)
      AND (d_month_seq BETWEEN 1200 AND (1200 + 11))
      AND (ws_item_sk IS NOT NULL)
   GROUP BY ws_item_sk, d_date
) 
, store_v1 AS (
   SELECT
     ss_item_sk item_sk
   , d_date
   , sum(sum(ss_sales_price)) OVER (PARTITION BY ss_item_sk ORDER BY d_date ASC ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) cume_sales
   FROM
     store_sales
   , date_dim
   WHERE (ss_sold_date_sk = d_date_sk)
      AND (d_month_seq BETWEEN 1200 AND (1200 + 11))
      AND (ss_item_sk IS NOT NULL)
   GROUP BY ss_item_sk, d_date
) 
SELECT *
FROM
  (
   SELECT
     item_sk
   , d_date
   , web_sales
   , store_sales
   , max(web_sales) OVER (PARTITION BY item_sk ORDER BY d_date ASC ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) web_cumulative
   , max(store_sales) OVER (PARTITION BY item_sk ORDER BY d_date ASC ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) store_cumulative
   FROM
     (
      SELECT
        (CASE WHEN (web.item_sk IS NOT NULL) THEN web.item_sk ELSE store.item_sk END) item_sk
      , (CASE WHEN (web.d_date IS NOT NULL) THEN web.d_date ELSE store.d_date END) d_date
      , web.cume_sales web_sales
      , store.cume_sales store_sales
      FROM
        (web_v1 web
      FULL JOIN store_v1 store ON (web.item_sk = store.item_sk)
         AND (web.d_date = store.d_date))
   )  x
)  y
WHERE (web_cumulative > store_cumulative)
ORDER BY item_sk ASC, d_date ASC
LIMIT 100
"""

QUERIES["q9"] = """
SELECT
  (CASE WHEN ((
      SELECT count(*)
      FROM
        store_sales
      WHERE (ss_quantity BETWEEN 1 AND 20)
   ) > 74129) THEN (
   SELECT avg(ss_ext_discount_amt)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 1 AND 20)
) ELSE (
   SELECT avg(ss_net_paid)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 1 AND 20)
) END) bucket1
, (CASE WHEN ((
      SELECT count(*)
      FROM
        store_sales
      WHERE (ss_quantity BETWEEN 21 AND 40)
   ) > 122840) THEN (
   SELECT avg(ss_ext_discount_amt)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 21 AND 40)
) ELSE (
   SELECT avg(ss_net_paid)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 21 AND 40)
) END) bucket2
, (CASE WHEN ((
      SELECT count(*)
      FROM
        store_sales
      WHERE (ss_quantity BETWEEN 41 AND 60)
   ) > 56580) THEN (
   SELECT avg(ss_ext_discount_amt)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 41 AND 60)
) ELSE (
   SELECT avg(ss_net_paid)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 41 AND 60)
) END) bucket3
, (CASE WHEN ((
      SELECT count(*)
      FROM
        store_sales
      WHERE (ss_quantity BETWEEN 61 AND 80)
   ) > 10097) THEN (
   SELECT avg(ss_ext_discount_amt)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 61 AND 80)
) ELSE (
   SELECT avg(ss_net_paid)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 61 AND 80)
) END) bucket4
, (CASE WHEN ((
      SELECT count(*)
      FROM
        store_sales
      WHERE (ss_quantity BETWEEN 81 AND 100)
   ) > 165306) THEN (
   SELECT avg(ss_ext_discount_amt)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 81 AND 100)
) ELSE (
   SELECT avg(ss_net_paid)
   FROM
     store_sales
   WHERE (ss_quantity BETWEEN 81 AND 100)
) END) bucket5
FROM
  reason
WHERE (r_reason_sk = 1)
"""

QUERIES["q10"] = """
SELECT
  cd_gender
, cd_marital_status
, cd_education_status
, count(*) cnt1
, cd_purchase_estimate
, count(*) cnt2
, cd_credit_rating
, count(*) cnt3
, cd_dep_count
, count(*) cnt4
, cd_dep_employed_count
, count(*) cnt5
, cd_dep_college_count
, count(*) cnt6
FROM
  customer c
, customer_address ca
, customer_demographics
WHERE (c.c_current_addr_sk = ca.ca_address_sk)
   AND (ca_county IN ('Rush County', 'Toole County', 'Jefferson County', 'Dona Ana County', 'La Porte County'))
   AND (cd_demo_sk = c.c_current_cdemo_sk)
   AND (EXISTS (
   SELECT *
   FROM
     store_sales
   , date_dim
   WHERE (c.c_customer_sk = ss_customer_sk)
      AND (ss_sold_date_sk = d_date_sk)
      AND (d_year = 2002)
      AND (d_moy BETWEEN 1 AND (1 + 3))
))
   AND ((EXISTS (
      SELECT *
      FROM
        web_sales
      , date_dim
      WHERE (c.c_customer_sk = ws_bill_customer_sk)
         AND (ws_sold_date_sk = d_date_sk)
         AND (d_year = 2002)
         AND (d_moy BETWEEN 1 AND (1 + 3))
   ))
      OR (EXISTS (
      SELECT *
      FROM
        catalog_sales
      , date_dim
      WHERE (c.c_customer_sk = cs_ship_customer_sk)
         AND (cs_sold_date_sk = d_date_sk)
         AND (d_year = 2002)
         AND (d_moy BETWEEN 1 AND (1 + 3))
   )))
GROUP BY cd_gender, cd_marital_status, cd_education_status, cd_purchase_estimate, cd_credit_rating, cd_dep_count, cd_dep_employed_count, cd_dep_college_count
ORDER BY cd_gender ASC, cd_marital_status ASC, cd_education_status ASC, cd_purchase_estimate ASC, cd_credit_rating ASC, cd_dep_count ASC, cd_dep_employed_count ASC, cd_dep_college_count ASC
LIMIT 100
"""

QUERIES["q35"] = """
SELECT
  ca_state
, cd_gender
, cd_marital_status
, cd_dep_count
, count(*) cnt1
, min(cd_dep_count)
, max(cd_dep_count)
, avg(cd_dep_count)
, cd_dep_employed_count
, count(*) cnt2
, min(cd_dep_employed_count)
, max(cd_dep_employed_count)
, avg(cd_dep_employed_count)
, cd_dep_college_count
, count(*) cnt3
, min(cd_dep_college_count)
, max(cd_dep_college_count)
, avg(cd_dep_college_count)
FROM
  customer c
, customer_address ca
, customer_demographics
WHERE (c.c_current_addr_sk = ca.ca_address_sk)
   AND (cd_demo_sk = c.c_current_cdemo_sk)
   AND (EXISTS (
   SELECT *
   FROM
     store_sales
   , date_dim
   WHERE (c.c_customer_sk = ss_customer_sk)
      AND (ss_sold_date_sk = d_date_sk)
      AND (d_year = 2002)
      AND (d_qoy < 4)
))
   AND ((EXISTS (
      SELECT *
      FROM
        web_sales
      , date_dim
      WHERE (c.c_customer_sk = ws_bill_customer_sk)
         AND (ws_sold_date_sk = d_date_sk)
         AND (d_year = 2002)
         AND (d_qoy < 4)
   ))
      OR (EXISTS (
      SELECT *
      FROM
        catalog_sales
      , date_dim
      WHERE (c.c_customer_sk = cs_ship_customer_sk)
         AND (cs_sold_date_sk = d_date_sk)
         AND (d_year = 2002)
         AND (d_qoy < 4)
   )))
GROUP BY ca_state, cd_gender, cd_marital_status, cd_dep_count, cd_dep_employed_count, cd_dep_college_count
ORDER BY ca_state ASC, cd_gender ASC, cd_marital_status ASC, cd_dep_count ASC, cd_dep_employed_count ASC, cd_dep_college_count ASC
LIMIT 100
"""

QUERIES["q45"] = """
SELECT
  ca_zip
, ca_city
, sum(ws_sales_price)
FROM
  web_sales
, customer
, customer_address
, date_dim
, item
WHERE (ws_bill_customer_sk = c_customer_sk)
   AND (c_current_addr_sk = ca_address_sk)
   AND (ws_item_sk = i_item_sk)
   AND ((substr(ca_zip, 1, 5) IN ('85669'   , '86197'   , '88274'   , '83405'   , '86475'   , '85392'   , '85460'   , '80348'   , '81792'))
      OR (i_item_id IN (
      SELECT i_item_id
      FROM
        item
      WHERE (i_item_sk IN (2      , 3      , 5      , 7      , 11      , 13      , 17      , 19      , 23      , 29))
   )))
   AND (ws_sold_date_sk = d_date_sk)
   AND (d_qoy = 2)
   AND (d_year = 2001)
GROUP BY ca_zip, ca_city
ORDER BY ca_zip ASC, ca_city ASC
LIMIT 100
"""

QUERIES["q74"] = """
WITH
  year_total AS (
   SELECT
     c_customer_id customer_id
   , c_first_name customer_first_name
   , c_last_name customer_last_name
   , d_year year_
   , sum(ss_net_paid) year_total
   , 's' sale_type
   FROM
     customer
   , store_sales
   , date_dim
   WHERE (c_customer_sk = ss_customer_sk)
      AND (ss_sold_date_sk = d_date_sk)
      AND (d_year IN (2001   , (2001 + 1)))
   GROUP BY c_customer_id, c_first_name, c_last_name, d_year
UNION ALL    SELECT
     c_customer_id customer_id
   , c_first_name customer_first_name
   , c_last_name customer_last_name
   , d_year year_
   , sum(ws_net_paid) year_total
   , 'w' sale_type
   FROM
     customer
   , web_sales
   , date_dim
   WHERE (c_customer_sk = ws_bill_customer_sk)
      AND (ws_sold_date_sk = d_date_sk)
      AND (d_year IN (2001   , (2001 + 1)))
   GROUP BY c_customer_id, c_first_name, c_last_name, d_year
) 
SELECT
  t_s_secyear.customer_id
, t_s_secyear.customer_first_name
, t_s_secyear.customer_last_name
FROM
  year_total t_s_firstyear
, year_total t_s_secyear
, year_total t_w_firstyear
, year_total t_w_secyear
WHERE (t_s_secyear.customer_id = t_s_firstyear.customer_id)
   AND (t_s_firstyear.customer_id = t_w_secyear.customer_id)
   AND (t_s_firstyear.customer_id = t_w_firstyear.customer_id)
   AND (t_s_firstyear.sale_type = 's')
   AND (t_w_firstyear.sale_type = 'w')
   AND (t_s_secyear.sale_type = 's')
   AND (t_w_secyear.sale_type = 'w')
   AND (t_s_firstyear.year_ = 2001)
   AND (t_s_secyear.year_ = (2001 + 1))
   AND (t_w_firstyear.year_ = 2001)
   AND (t_w_secyear.year_ = (2001 + 1))
   AND (t_s_firstyear.year_total > 0)
   AND (t_w_firstyear.year_total > 0)
   AND ((CASE WHEN (t_w_firstyear.year_total > 0) THEN (t_w_secyear.year_total / t_w_firstyear.year_total) ELSE null END) > (CASE WHEN (t_s_firstyear.year_total > 0) THEN (t_s_secyear.year_total / t_s_firstyear.year_total) ELSE null END))
ORDER BY 1 ASC, 1 ASC, 1 ASC
LIMIT 100
"""

QUERIES["q87"] = """
SELECT count(*)
FROM
  (
(
      SELECT DISTINCT
        c_last_name
      , c_first_name
      , d_date
      FROM
        store_sales
      , date_dim
      , customer
      WHERE (store_sales.ss_sold_date_sk = date_dim.d_date_sk)
         AND (store_sales.ss_customer_sk = customer.c_customer_sk)
         AND (d_month_seq BETWEEN 1200 AND (1200 + 11))
   ) EXCEPT (
      SELECT DISTINCT
        c_last_name
      , c_first_name
      , d_date
      FROM
        catalog_sales
      , date_dim
      , customer
      WHERE (catalog_sales.cs_sold_date_sk = date_dim.d_date_sk)
         AND (catalog_sales.cs_bill_customer_sk = customer.c_customer_sk)
         AND (d_month_seq BETWEEN 1200 AND (1200 + 11))
   ) EXCEPT (
      SELECT DISTINCT
        c_last_name
      , c_first_name
      , d_date
      FROM
        web_sales
      , date_dim
      , customer
      WHERE (web_sales.ws_sold_date_sk = date_dim.d_date_sk)
         AND (web_sales.ws_bill_customer_sk = customer.c_customer_sk)
         AND (d_month_seq BETWEEN 1200 AND (1200 + 11))
   ) )  cool_cust
"""

QUERIES["q14"] = """
WITH
  cross_items AS (
   SELECT i_item_sk ss_item_sk
   FROM
     item
   , (
      SELECT
        iss.i_brand_id brand_id
      , iss.i_class_id class_id
      , iss.i_category_id category_id
      FROM
        store_sales
      , item iss
      , date_dim d1
      WHERE (ss_item_sk = iss.i_item_sk)
         AND (ss_sold_date_sk = d1.d_date_sk)
         AND (d1.d_year BETWEEN 1999 AND (1999 + 2))
INTERSECT       SELECT
        ics.i_brand_id
      , ics.i_class_id
      , ics.i_category_id
      FROM
        catalog_sales
      , item ics
      , date_dim d2
      WHERE (cs_item_sk = ics.i_item_sk)
         AND (cs_sold_date_sk = d2.d_date_sk)
         AND (d2.d_year BETWEEN 1999 AND (1999 + 2))
INTERSECT       SELECT
        iws.i_brand_id
      , iws.i_class_id
      , iws.i_category_id
      FROM
        web_sales
      , item iws
      , date_dim d3
      WHERE (ws_item_sk = iws.i_item_sk)
         AND (ws_sold_date_sk = d3.d_date_sk)
         AND (d3.d_year BETWEEN 1999 AND (1999 + 2))
   ) 
   WHERE (i_brand_id = brand_id)
      AND (i_class_id = class_id)
      AND (i_category_id = category_id)
) 
, avg_sales AS (
   SELECT avg((quantity * list_price)) average_sales
   FROM
     (
      SELECT
        ss_quantity quantity
      , ss_list_price list_price
      FROM
        store_sales
      , date_dim
      WHERE (ss_sold_date_sk = d_date_sk)
         AND (d_year BETWEEN 1999 AND (1999 + 2))
UNION ALL       SELECT
        cs_quantity quantity
      , cs_list_price list_price
      FROM
        catalog_sales
      , date_dim
      WHERE (cs_sold_date_sk = d_date_sk)
         AND (d_year BETWEEN 1999 AND (1999 + 2))
UNION ALL       SELECT
        ws_quantity quantity
      , ws_list_price list_price
      FROM
        web_sales
      , date_dim
      WHERE (ws_sold_date_sk = d_date_sk)
         AND (d_year BETWEEN 1999 AND (1999 + 2))
   )  x
) 
SELECT
  channel
, i_brand_id
, i_class_id
, i_category_id
, sum(sales)
, sum(number_sales)
FROM
  (
   SELECT
     'store' channel
   , i_brand_id
   , i_class_id
   , i_category_id
   , sum((ss_quantity * ss_list_price)) sales
   , count(*) number_sales
   FROM
     store_sales
   , item
   , date_dim
   WHERE (ss_item_sk IN (
      SELECT ss_item_sk
      FROM
        cross_items
   ))
      AND (ss_item_sk = i_item_sk)
      AND (ss_sold_date_sk = d_date_sk)
      AND (d_year = (1999 + 2))
      AND (d_moy = 11)
   GROUP BY i_brand_id, i_class_id, i_category_id
   HAVING (sum((ss_quantity * ss_list_price)) > (
         SELECT average_sales
         FROM
           avg_sales
      ))
UNION ALL    SELECT
     'catalog' channel
   , i_brand_id
   , i_class_id
   , i_category_id
   , sum((cs_quantity * cs_list_price)) sales
   , count(*) number_sales
   FROM
     catalog_sales
   , item
   , date_dim
   WHERE (cs_item_sk IN (
      SELECT ss_item_sk
      FROM
        cross_items
   ))
      AND (cs_item_sk = i_item_sk)
      AND (cs_sold_date_sk = d_date_sk)
      AND (d_year = (1999 + 2))
      AND (d_moy = 11)
   GROUP BY i_brand_id, i_class_id, i_category_id
   HAVING (sum((cs_quantity * cs_list_price)) > (
         SELECT average_sales
         FROM
           avg_sales
      ))
UNION ALL    SELECT
     'web' channel
   , i_brand_id
   , i_class_id
   , i_category_id
   , sum((ws_quantity * ws_list_price)) sales
   , count(*) number_sales
   FROM
     web_sales
   , item
   , date_dim
   WHERE (ws_item_sk IN (
      SELECT ss_item_sk
      FROM
        cross_items
   ))
      AND (ws_item_sk = i_item_sk)
      AND (ws_sold_date_sk = d_date_sk)
      AND (d_year = (1999 + 2))
      AND (d_moy = 11)
   GROUP BY i_brand_id, i_class_id, i_category_id
   HAVING (sum((ws_quantity * ws_list_price)) > (
         SELECT average_sales
         FROM
           avg_sales
      ))
)  y
GROUP BY ROLLUP (channel, i_brand_id, i_class_id, i_category_id)
ORDER BY channel ASC, i_brand_id ASC, i_class_id ASC, i_category_id ASC
LIMIT 100
"""

QUERIES["q70"] = """
SELECT
  sum(ss_net_profit) total_sum
, s_state
, s_county
, (GROUPING (s_state) + GROUPING (s_county)) lochierarchy
, rank() OVER (PARTITION BY (GROUPING (s_state) + GROUPING (s_county)), (CASE WHEN (GROUPING (s_county) = 0) THEN s_state END) ORDER BY sum(ss_net_profit) DESC) rank_within_parent
FROM
  store_sales
, date_dim d1
, store
WHERE (d1.d_month_seq BETWEEN 1200 AND (1200 + 11))
   AND (d1.d_date_sk = ss_sold_date_sk)
   AND (s_store_sk = ss_store_sk)
   AND (s_state IN (
   SELECT s_state
   FROM
     (
      SELECT
        s_state s_state
      , rank() OVER (PARTITION BY s_state ORDER BY sum(ss_net_profit) DESC) ranking
      FROM
        store_sales
      , store
      , date_dim
      WHERE (d_month_seq BETWEEN 1200 AND (1200 + 11))
         AND (d_date_sk = ss_sold_date_sk)
         AND (s_store_sk = ss_store_sk)
      GROUP BY s_state
   )  tmp1
   WHERE (ranking <= 5)
))
GROUP BY ROLLUP (s_state, s_county)
ORDER BY lochierarchy DESC, (CASE WHEN (lochierarchy = 0) THEN s_state END) ASC, rank_within_parent ASC
LIMIT 100
"""

# ROLLUP/GROUPING hand-spelled as UNION ALL levels for the
# sqlite oracle (sqlite has no grouping sets)
SQLITE_ORACLE["q14"] = """
WITH
  cross_items AS (
   SELECT i_item_sk ss_item_sk
   FROM
     item
   , (
      SELECT
        iss.i_brand_id brand_id
      , iss.i_class_id class_id
      , iss.i_category_id category_id
      FROM
        store_sales
      , item iss
      , date_dim d1
      WHERE (ss_item_sk = iss.i_item_sk)
         AND (ss_sold_date_sk = d1.d_date_sk)
         AND (d1.d_year BETWEEN 1999 AND (1999 + 2))
INTERSECT       SELECT
        ics.i_brand_id
      , ics.i_class_id
      , ics.i_category_id
      FROM
        catalog_sales
      , item ics
      , date_dim d2
      WHERE (cs_item_sk = ics.i_item_sk)
         AND (cs_sold_date_sk = d2.d_date_sk)
         AND (d2.d_year BETWEEN 1999 AND (1999 + 2))
INTERSECT       SELECT
        iws.i_brand_id
      , iws.i_class_id
      , iws.i_category_id
      FROM
        web_sales
      , item iws
      , date_dim d3
      WHERE (ws_item_sk = iws.i_item_sk)
         AND (ws_sold_date_sk = d3.d_date_sk)
         AND (d3.d_year BETWEEN 1999 AND (1999 + 2))
   ) 
   WHERE (i_brand_id = brand_id)
      AND (i_class_id = class_id)
      AND (i_category_id = category_id)
) 
, avg_sales AS (
   SELECT avg((quantity * list_price)) average_sales
   FROM
     (
      SELECT
        ss_quantity quantity
      , ss_list_price list_price
      FROM
        store_sales
      , date_dim
      WHERE (ss_sold_date_sk = d_date_sk)
         AND (d_year BETWEEN 1999 AND (1999 + 2))
UNION ALL       SELECT
        cs_quantity quantity
      , cs_list_price list_price
      FROM
        catalog_sales
      , date_dim
      WHERE (cs_sold_date_sk = d_date_sk)
         AND (d_year BETWEEN 1999 AND (1999 + 2))
UNION ALL       SELECT
        ws_quantity quantity
      , ws_list_price list_price
      FROM
        web_sales
      , date_dim
      WHERE (ws_sold_date_sk = d_date_sk)
         AND (d_year BETWEEN 1999 AND (1999 + 2))
   )  x
)
, y AS (

   SELECT
     'store' channel
   , i_brand_id
   , i_class_id
   , i_category_id
   , sum((ss_quantity * ss_list_price)) sales
   , count(*) number_sales
   FROM
     store_sales
   , item
   , date_dim
   WHERE (ss_item_sk IN (
      SELECT ss_item_sk
      FROM
        cross_items
   ))
      AND (ss_item_sk = i_item_sk)
      AND (ss_sold_date_sk = d_date_sk)
      AND (d_year = (1999 + 2))
      AND (d_moy = 11)
   GROUP BY i_brand_id, i_class_id, i_category_id
   HAVING (sum((ss_quantity * ss_list_price)) > (
         SELECT average_sales
         FROM
           avg_sales
      ))
UNION ALL    SELECT
     'catalog' channel
   , i_brand_id
   , i_class_id
   , i_category_id
   , sum((cs_quantity * cs_list_price)) sales
   , count(*) number_sales
   FROM
     catalog_sales
   , item
   , date_dim
   WHERE (cs_item_sk IN (
      SELECT ss_item_sk
      FROM
        cross_items
   ))
      AND (cs_item_sk = i_item_sk)
      AND (cs_sold_date_sk = d_date_sk)
      AND (d_year = (1999 + 2))
      AND (d_moy = 11)
   GROUP BY i_brand_id, i_class_id, i_category_id
   HAVING (sum((cs_quantity * cs_list_price)) > (
         SELECT average_sales
         FROM
           avg_sales
      ))
UNION ALL    SELECT
     'web' channel
   , i_brand_id
   , i_class_id
   , i_category_id
   , sum((ws_quantity * ws_list_price)) sales
   , count(*) number_sales
   FROM
     web_sales
   , item
   , date_dim
   WHERE (ws_item_sk IN (
      SELECT ss_item_sk
      FROM
        cross_items
   ))
      AND (ws_item_sk = i_item_sk)
      AND (ws_sold_date_sk = d_date_sk)
      AND (d_year = (1999 + 2))
      AND (d_moy = 11)
   GROUP BY i_brand_id, i_class_id, i_category_id
   HAVING (sum((ws_quantity * ws_list_price)) > (
         SELECT average_sales
         FROM
           avg_sales
      ))
)
SELECT channel, i_brand_id, i_class_id, i_category_id,
       sum(sales), sum(number_sales)
FROM (
  select channel, i_brand_id, i_class_id, i_category_id, sales, number_sales from y
  union all
  select channel, i_brand_id, i_class_id, null, sales, number_sales from y
  union all
  select channel, i_brand_id, null, null, sales, number_sales from y
  union all
  select channel, null, null, null, sales, number_sales from y
  union all
  select null, null, null, null, sales, number_sales from y
) z
GROUP BY channel, i_brand_id, i_class_id, i_category_id
ORDER BY channel ASC NULLS LAST, i_brand_id ASC NULLS LAST,
         i_class_id ASC NULLS LAST, i_category_id ASC NULLS LAST
LIMIT 100
"""

SQLITE_ORACLE["q70"] = """
with base as (
  select s_state st, s_county cty, sum(ss_net_profit) np
  from store_sales, date_dim d1, store
  where d1.d_month_seq between 1200 and 1211
    and d1.d_date_sk = ss_sold_date_sk
    and s_store_sk = ss_store_sk
    and s_state in (
      select s_state from (
        select s_state,
               rank() over (partition by s_state
                            order by sum(ss_net_profit) desc) ranking
        from store_sales, store, date_dim
        where d_month_seq between 1200 and 1211
          and d_date_sk = ss_sold_date_sk
          and s_store_sk = ss_store_sk
        group by s_state) tmp1
      where ranking <= 5)
  group by s_state, s_county
), lvl as (
  select np total_sum, st s_state, cty s_county, 0 lochierarchy from base
  union all
  select sum(np), st, null, 1 from base group by st
  union all
  select sum(np), null, null, 2 from base
)
select total_sum, s_state, s_county, lochierarchy,
       rank() over (partition by lochierarchy,
                    case when lochierarchy = 0 then s_state end
                    order by total_sum desc) rank_within_parent
from lvl
order by lochierarchy desc,
         case when lochierarchy = 0 then s_state end asc nulls last,
         rank_within_parent asc
limit 100
"""

QUERIES["q41"] = """
SELECT DISTINCT i_product_name
FROM
  item i1
WHERE (i_manufact_id BETWEEN 738 AND (738 + 40))
   AND ((
      SELECT count(*) item_cnt
      FROM
        item
      WHERE ((i_manufact = i1.i_manufact)
            AND (((i_category = 'Women')
                  AND ((i_color = 'powder')
                     OR (i_color = 'khaki'))
                  AND ((i_units = 'Ounce')
                     OR (i_units = 'Oz'))
                  AND ((i_size = 'medium')
                     OR (i_size = 'extra large')))
               OR ((i_category = 'Women')
                  AND ((i_color = 'brown')
                     OR (i_color = 'honeydew'))
                  AND ((i_units = 'Bunch')
                     OR (i_units = 'Ton'))
                  AND ((i_size = 'N/A')
                     OR (i_size = 'small')))
               OR ((i_category = 'Men')
                  AND ((i_color = 'floral')
                     OR (i_color = 'deep'))
                  AND ((i_units = 'N/A')
                     OR (i_units = 'Dozen'))
                  AND ((i_size = 'petite')
                     OR (i_size = 'large')))
               OR ((i_category = 'Men')
                  AND ((i_color = 'light')
                     OR (i_color = 'cornflower'))
                  AND ((i_units = 'Box')
                     OR (i_units = 'Pound'))
                  AND ((i_size = 'medium')
                     OR (i_size = 'extra large')))))
         OR ((i_manufact = i1.i_manufact)
            AND (((i_category = 'Women')
                  AND ((i_color = 'midnight')
                     OR (i_color = 'snow'))
                  AND ((i_units = 'Pallet')
                     OR (i_units = 'Gross'))
                  AND ((i_size = 'medium')
                     OR (i_size = 'extra large')))
               OR ((i_category = 'Women')
                  AND ((i_color = 'cyan')
                     OR (i_color = 'papaya'))
                  AND ((i_units = 'Cup')
                     OR (i_units = 'Dram'))
                  AND ((i_size = 'N/A')
                     OR (i_size = 'small')))
               OR ((i_category = 'Men')
                  AND ((i_color = 'orange')
                     OR (i_color = 'frosted'))
                  AND ((i_units = 'Each')
                     OR (i_units = 'Tbl'))
                  AND ((i_size = 'petite')
                     OR (i_size = 'large')))
               OR ((i_category = 'Men')
                  AND ((i_color = 'forest')
                     OR (i_color = 'ghost'))
                  AND ((i_units = 'Lb')
                     OR (i_units = 'Bundle'))
                  AND ((i_size = 'medium')
                     OR (i_size = 'extra large')))))
   ) > 0)
ORDER BY i_product_name ASC
LIMIT 100
"""

QUERIES["q75"] = """
WITH
  all_sales AS (
   SELECT
     d_year
   , i_brand_id
   , i_class_id
   , i_category_id
   , i_manufact_id
   , sum(sales_cnt) sales_cnt
   , sum(sales_amt) sales_amt
   FROM
     (
      SELECT
        d_year
      , i_brand_id
      , i_class_id
      , i_category_id
      , i_manufact_id
      , (cs_quantity - COALESCE(cr_return_quantity, 0)) sales_cnt
      , (cs_ext_sales_price - COALESCE(cr_return_amount, 0.0)) sales_amt
      FROM
        (((catalog_sales
      INNER JOIN item ON (i_item_sk = cs_item_sk))
      INNER JOIN date_dim ON (d_date_sk = cs_sold_date_sk))
      LEFT JOIN catalog_returns ON (cs_order_number = cr_order_number)
         AND (cs_item_sk = cr_item_sk))
      WHERE (i_category = 'Books')
UNION       SELECT
        d_year
      , i_brand_id
      , i_class_id
      , i_category_id
      , i_manufact_id
      , (ss_quantity - COALESCE(sr_return_quantity, 0)) sales_cnt
      , (ss_ext_sales_price - COALESCE(sr_return_amt, 0.0)) sales_amt
      FROM
        (((store_sales
      INNER JOIN item ON (i_item_sk = ss_item_sk))
      INNER JOIN date_dim ON (d_date_sk = ss_sold_date_sk))
      LEFT JOIN store_returns ON (ss_ticket_number = sr_ticket_number)
         AND (ss_item_sk = sr_item_sk))
      WHERE (i_category = 'Books')
UNION       SELECT
        d_year
      , i_brand_id
      , i_class_id
      , i_category_id
      , i_manufact_id
      , (ws_quantity - COALESCE(wr_return_quantity, 0)) sales_cnt
      , (ws_ext_sales_price - COALESCE(wr_return_amt, 0.0)) sales_amt
      FROM
        (((web_sales
      INNER JOIN item ON (i_item_sk = ws_item_sk))
      INNER JOIN date_dim ON (d_date_sk = ws_sold_date_sk))
      LEFT JOIN web_returns ON (ws_order_number = wr_order_number)
         AND (ws_item_sk = wr_item_sk))
      WHERE (i_category = 'Books')
   )  sales_detail
   GROUP BY d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id
) 
SELECT
  prev_yr.d_year prev_year
, curr_yr.d_year year_
, curr_yr.i_brand_id
, curr_yr.i_class_id
, curr_yr.i_category_id
, curr_yr.i_manufact_id
, prev_yr.sales_cnt prev_yr_cnt
, curr_yr.sales_cnt curr_yr_cnt
, (curr_yr.sales_cnt - prev_yr.sales_cnt) sales_cnt_diff
, (curr_yr.sales_amt - prev_yr.sales_amt) sales_amt_diff
FROM
  all_sales curr_yr
, all_sales prev_yr
WHERE (curr_yr.i_brand_id = prev_yr.i_brand_id)
   AND (curr_yr.i_class_id = prev_yr.i_class_id)
   AND (curr_yr.i_category_id = prev_yr.i_category_id)
   AND (curr_yr.i_manufact_id = prev_yr.i_manufact_id)
   AND (curr_yr.d_year = 2002)
   AND (prev_yr.d_year = (2002 - 1))
   AND ((CAST(curr_yr.sales_cnt AS DECIMAL(17,2)) / CAST(prev_yr.sales_cnt AS DECIMAL(17,2))) < 0.9)
ORDER BY sales_cnt_diff ASC, sales_amt_diff ASC
LIMIT 100
"""

QUERIES["q78"] = """
WITH
  ws AS (
   SELECT
     d_year ws_sold_year
   , ws_item_sk
   , ws_bill_customer_sk ws_customer_sk
   , sum(ws_quantity) ws_qty
   , sum(ws_wholesale_cost) ws_wc
   , sum(ws_sales_price) ws_sp
   FROM
     ((web_sales
   LEFT JOIN web_returns ON (wr_order_number = ws_order_number)
      AND (ws_item_sk = wr_item_sk))
   INNER JOIN date_dim ON (ws_sold_date_sk = d_date_sk))
   WHERE (wr_order_number IS NULL)
   GROUP BY d_year, ws_item_sk, ws_bill_customer_sk
) 
, cs AS (
   SELECT
     d_year cs_sold_year
   , cs_item_sk
   , cs_bill_customer_sk cs_customer_sk
   , sum(cs_quantity) cs_qty
   , sum(cs_wholesale_cost) cs_wc
   , sum(cs_sales_price) cs_sp
   FROM
     ((catalog_sales
   LEFT JOIN catalog_returns ON (cr_order_number = cs_order_number)
      AND (cs_item_sk = cr_item_sk))
   INNER JOIN date_dim ON (cs_sold_date_sk = d_date_sk))
   WHERE (cr_order_number IS NULL)
   GROUP BY d_year, cs_item_sk, cs_bill_customer_sk
) 
, ss AS (
   SELECT
     d_year ss_sold_year
   , ss_item_sk
   , ss_customer_sk
   , sum(ss_quantity) ss_qty
   , sum(ss_wholesale_cost) ss_wc
   , sum(ss_sales_price) ss_sp
   FROM
     ((store_sales
   LEFT JOIN store_returns ON (sr_ticket_number = ss_ticket_number)
      AND (ss_item_sk = sr_item_sk))
   INNER JOIN date_dim ON (ss_sold_date_sk = d_date_sk))
   WHERE (sr_ticket_number IS NULL)
   GROUP BY d_year, ss_item_sk, ss_customer_sk
) 
SELECT
  ss_sold_year
, ss_item_sk
, ss_customer_sk
, round((CAST(ss_qty AS DECIMAL(10,2)) / COALESCE((ws_qty + cs_qty), 1)), 2) ratio
, ss_qty store_qty
, ss_wc store_wholesale_cost
, ss_sp store_sales_price
, (COALESCE(ws_qty, 0) + COALESCE(cs_qty, 0)) other_chan_qty
, (COALESCE(ws_wc, 0) + COALESCE(cs_wc, 0)) other_chan_wholesale_cost
, (COALESCE(ws_sp, 0) + COALESCE(cs_sp, 0)) other_chan_sales_price
FROM
  ((ss
LEFT JOIN ws ON (ws_sold_year = ss_sold_year)
   AND (ws_item_sk = ss_item_sk)
   AND (ws_customer_sk = ss_customer_sk))
LEFT JOIN cs ON (cs_sold_year = ss_sold_year)
   AND (cs_item_sk = cs_item_sk)
   AND (cs_customer_sk = ss_customer_sk))
WHERE (COALESCE(ws_qty, 0) > 0)
   AND (COALESCE(cs_qty, 0) > 0)
   AND (ss_sold_year = 2000)
ORDER BY ss_sold_year ASC, ss_item_sk ASC, ss_customer_sk ASC, ss_qty DESC, ss_wc DESC, ss_sp DESC, other_chan_qty ASC, other_chan_wholesale_cost ASC, other_chan_sales_price ASC, round((CAST(ss_qty AS DECIMAL(10,2)) / COALESCE((ws_qty + cs_qty), 1)), 2) ASC
LIMIT 100
"""

QUERIES["q84"] = """
SELECT
  c_customer_id customer_id
, concat(concat(c_last_name, ', '), c_first_name) customername
FROM
  customer
, customer_address
, customer_demographics
, household_demographics
, income_band
, store_returns
WHERE (ca_city = 'Edgewood')
   AND (c_current_addr_sk = ca_address_sk)
   AND (ib_lower_bound >= 38128)
   AND (ib_upper_bound <= (38128 + 50000))
   AND (ib_income_band_sk = hd_income_band_sk)
   AND (cd_demo_sk = c_current_cdemo_sk)
   AND (hd_demo_sk = c_current_hdemo_sk)
   AND (sr_cdemo_sk = cd_demo_sk)
ORDER BY c_customer_id ASC
LIMIT 100
"""

