"""Canonical TPC-DS query texts (spec templates with standard
parameter substitutions), restated in the engine dialect.

The analog of the reference's TPC-DS benchmark query set
(testing/trino-benchto-benchmarks/.../benchmarks/trino/tpcds.yaml).
Includes the BASELINE config #4 queries Q72 (deep 11-relation join
tree over catalog_sales x inventory) and Q95 (web_sales self-join CTE
+ IN-subqueries). Date-window parameters are aligned to the
generator's 1998-2002 sales calendar.
"""

QUERIES: dict[str, str] = {}

QUERIES["q3"] = """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manufact_id = 128
  and d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, 4 desc, brand_id
limit 100
"""

QUERIES["q7"] = """
select i_item_id,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""

QUERIES["q19"] = """
select i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 8
  and d_moy = 11
  and d_year = 1998
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
  and ss_store_sk = s_store_sk
group by i_brand_id, i_brand, i_manufact_id, i_manufact
order by 5 desc, brand, brand_id, i_manufact_id, i_manufact
limit 100
"""

QUERIES["q25"] = """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) as store_sales_profit,
       sum(sr_net_loss) as store_returns_loss,
       sum(cs_net_profit) as catalog_sales_profit
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_moy = 4
  and d1.d_year = 2001
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 4 and 10
  and d2.d_year = 2001
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_moy between 4 and 10
  and d3.d_year = 2001
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""

QUERIES["q42"] = """
select dt.d_year, item.i_category_id, item.i_category,
       sum(ss_ext_sales_price)
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11
  and dt.d_year = 2000
group by dt.d_year, item.i_category_id, item.i_category
order by 4 desc, 1, 2, 3
limit 100
"""

QUERIES["q52"] = """
select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11
  and dt.d_year = 2000
group by dt.d_year, item.i_brand_id, item.i_brand
order by 1, 4 desc, 2
limit 100
"""

QUERIES["q55"] = """
select i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 28
  and d_moy = 11
  and d_year = 1999
group by i_brand_id, i_brand
order by 3 desc, brand_id
limit 100
"""

QUERIES["q62"] = """
select w_warehouse_name, sm_type, web_name,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk <= 30)
      then 1 else 0 end) as d30,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 30)
       and (ws_ship_date_sk - ws_sold_date_sk <= 60)
      then 1 else 0 end) as d60,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 60)
       and (ws_ship_date_sk - ws_sold_date_sk <= 90)
      then 1 else 0 end) as d90,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 90)
       and (ws_ship_date_sk - ws_sold_date_sk <= 120)
      then 1 else 0 end) as d120,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 120)
      then 1 else 0 end) as dmore
from web_sales, warehouse, ship_mode, web_site, date_dim
where d_month_seq between 132 and 143
  and ws_ship_date_sk = d_date_sk
  and ws_warehouse_sk = w_warehouse_sk
  and ws_ship_mode_sk = sm_ship_mode_sk
  and ws_web_site_sk = web_site_sk
group by w_warehouse_name, sm_type, web_name
order by 1, 2, 3
limit 100
"""

QUERIES["q68"] = """
select c_last_name, c_first_name, ca_city, bought_city,
       ss_ticket_number, extended_price, extended_tax, list_price
from (
    select ss_ticket_number, ss_customer_sk, ca_city bought_city,
           sum(ss_ext_sales_price) extended_price,
           sum(ss_ext_list_price) list_price,
           sum(ss_ext_tax) extended_tax
    from store_sales, date_dim, store, household_demographics,
         customer_address
    where ss_sold_date_sk = d_date_sk
      and ss_store_sk = s_store_sk
      and ss_hdemo_sk = hd_demo_sk
      and ss_addr_sk = ca_address_sk
      and d_dom between 1 and 2
      and (hd_dep_count = 4 or hd_vehicle_count = 3)
      and d_year in (1999, 2000, 2001)
      and s_city in ('Fairview', 'Midway')
    group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city
) dn, customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, ss_ticket_number
limit 100
"""

QUERIES["q72"] = """
select i_item_desc, w_warehouse_name, d1.d_week_seq,
       sum(case when p_promo_sk is null then 1 else 0 end) no_promo,
       sum(case when p_promo_sk is not null then 1 else 0 end) promo,
       count(*) total_cnt
from catalog_sales
join inventory on cs_item_sk = inv_item_sk
join warehouse on w_warehouse_sk = inv_warehouse_sk
join item on i_item_sk = cs_item_sk
join customer_demographics on cs_bill_cdemo_sk = cd_demo_sk
join household_demographics on cs_bill_hdemo_sk = hd_demo_sk
join date_dim d1 on cs_sold_date_sk = d1.d_date_sk
join date_dim d2 on inv_date_sk = d2.d_date_sk
join date_dim d3 on cs_ship_date_sk = d3.d_date_sk
left outer join promotion on cs_promo_sk = p_promo_sk
left outer join catalog_returns on cr_item_sk = cs_item_sk
  and cr_order_number = cs_order_number
where d1.d_week_seq = d2.d_week_seq
  and inv_quantity_on_hand < cs_quantity
  and d3.d_date > d1.d_date + 5
  and hd_buy_potential = '>10000'
  and d1.d_year = 1999
  and cd_marital_status = 'D'
group by i_item_desc, w_warehouse_name, d1.d_week_seq
order by 6 desc, 1, 2, 3
limit 100
"""

QUERIES["q95"] = """
with ws_wh as (
    select ws1.ws_order_number wh_order_number
    from web_sales ws1, web_sales ws2
    where ws1.ws_order_number = ws2.ws_order_number
      and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk
)
select count(distinct ws_order_number) as order_count,
       sum(ws_ext_ship_cost) as total_shipping_cost,
       sum(ws_net_profit) as total_net_profit
from web_sales, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and date '1999-04-02'
  and ws_ship_date_sk = d_date_sk
  and ws_ship_addr_sk = ca_address_sk
  and ca_state = 'IL'
  and ws_web_site_sk = web_site_sk
  and web_company_name = 'pri'
  and ws_order_number in (select wh_order_number from ws_wh)
  and ws_order_number in (
      select wr_order_number from web_returns, ws_wh
      where wr_order_number = wh_order_number
  )
"""

QUERIES["q96"] = """
select count(*)
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = t_time_sk
  and ss_hdemo_sk = hd_demo_sk
  and ss_store_sk = s_store_sk
  and t_hour = 20
  and t_minute >= 30
  and hd_dep_count = 7
  and s_store_name = 'ese'
"""

QUERIES["q98"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) as itemrevenue,
       sum(ss_ext_sales_price) * 100 / sum(sum(ss_ext_sales_price))
           over (partition by i_class) as revenueratio
from store_sales, item, date_dim
where ss_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ss_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-03-24'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, 7
limit 100
"""

QUERIES["q37"] = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, catalog_sales
where i_current_price between 10 and 150
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-02-01' and date '2000-04-01'
  and i_manufact_id in (810, 872, 215, 901)
  and inv_quantity_on_hand between 100 and 500
  and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""

QUERIES["q82"] = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, store_sales
where i_current_price between 10 and 150
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-05-25' and date '2000-07-24'
  and i_manufact_id in (990, 465, 354, 497)
  and inv_quantity_on_hand between 100 and 500
  and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""

QUERIES["q99"] = """
select w_warehouse_name, sm_type, cc_name,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk <= 30)
      then 1 else 0 end) as d30,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 30)
       and (cs_ship_date_sk - cs_sold_date_sk <= 60)
      then 1 else 0 end) as d60,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 60)
       and (cs_ship_date_sk - cs_sold_date_sk <= 90)
      then 1 else 0 end) as d90,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 90)
       and (cs_ship_date_sk - cs_sold_date_sk <= 120)
      then 1 else 0 end) as d120,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 120)
      then 1 else 0 end) as dmore
from catalog_sales, warehouse, ship_mode, call_center, date_dim
where d_month_seq between 132 and 143
  and cs_ship_date_sk = d_date_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_ship_mode_sk = sm_ship_mode_sk
  and cs_call_center_sk = cc_call_center_sk
group by w_warehouse_name, sm_type, cc_name
order by 1, 2, 3
limit 100
"""
