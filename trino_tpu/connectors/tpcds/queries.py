"""Canonical TPC-DS query texts (spec templates with standard
parameter substitutions), restated in the engine dialect.

The analog of the reference's TPC-DS benchmark query set
(testing/trino-benchto-benchmarks/.../benchmarks/trino/tpcds.yaml).
Includes the BASELINE config #4 queries Q72 (deep 11-relation join
tree over catalog_sales x inventory) and Q95 (web_sales self-join CTE
+ IN-subqueries). Date-window parameters are aligned to the
generator's 1998-2002 sales calendar.
"""

QUERIES: dict[str, str] = {}

QUERIES["q3"] = """
select d_year, i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manufact_id = 128
  and d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, 4 desc, brand_id
limit 100
"""

QUERIES["q7"] = """
select i_item_id,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""

QUERIES["q19"] = """
select i_brand_id brand_id, i_brand brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 8
  and d_moy = 11
  and d_year = 1998
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
  and ss_store_sk = s_store_sk
group by i_brand_id, i_brand, i_manufact_id, i_manufact
order by 5 desc, brand, brand_id, i_manufact_id, i_manufact
limit 100
"""

QUERIES["q25"] = """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) as store_sales_profit,
       sum(sr_net_loss) as store_returns_loss,
       sum(cs_net_profit) as catalog_sales_profit
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_moy = 4
  and d1.d_year = 2001
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and ss_customer_sk = sr_customer_sk
  and ss_item_sk = sr_item_sk
  and ss_ticket_number = sr_ticket_number
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_moy between 4 and 10
  and d2.d_year = 2001
  and sr_customer_sk = cs_bill_customer_sk
  and sr_item_sk = cs_item_sk
  and cs_sold_date_sk = d3.d_date_sk
  and d3.d_moy between 4 and 10
  and d3.d_year = 2001
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
"""

QUERIES["q42"] = """
select dt.d_year, item.i_category_id, item.i_category,
       sum(ss_ext_sales_price)
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11
  and dt.d_year = 2000
group by dt.d_year, item.i_category_id, item.i_category
order by 4 desc, 1, 2, 3
limit 100
"""

QUERIES["q52"] = """
select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11
  and dt.d_year = 2000
group by dt.d_year, item.i_brand_id, item.i_brand
order by 1, 4 desc, 2
limit 100
"""

QUERIES["q55"] = """
select i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 28
  and d_moy = 11
  and d_year = 1999
group by i_brand_id, i_brand
order by 3 desc, brand_id
limit 100
"""

QUERIES["q62"] = """
select w_warehouse_name, sm_type, web_name,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk <= 30)
      then 1 else 0 end) as d30,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 30)
       and (ws_ship_date_sk - ws_sold_date_sk <= 60)
      then 1 else 0 end) as d60,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 60)
       and (ws_ship_date_sk - ws_sold_date_sk <= 90)
      then 1 else 0 end) as d90,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 90)
       and (ws_ship_date_sk - ws_sold_date_sk <= 120)
      then 1 else 0 end) as d120,
  sum(case when (ws_ship_date_sk - ws_sold_date_sk > 120)
      then 1 else 0 end) as dmore
from web_sales, warehouse, ship_mode, web_site, date_dim
where d_month_seq between 132 and 143
  and ws_ship_date_sk = d_date_sk
  and ws_warehouse_sk = w_warehouse_sk
  and ws_ship_mode_sk = sm_ship_mode_sk
  and ws_web_site_sk = web_site_sk
group by w_warehouse_name, sm_type, web_name
order by 1, 2, 3
limit 100
"""

QUERIES["q68"] = """
select c_last_name, c_first_name, ca_city, bought_city,
       ss_ticket_number, extended_price, extended_tax, list_price
from (
    select ss_ticket_number, ss_customer_sk, ca_city bought_city,
           sum(ss_ext_sales_price) extended_price,
           sum(ss_ext_list_price) list_price,
           sum(ss_ext_tax) extended_tax
    from store_sales, date_dim, store, household_demographics,
         customer_address
    where ss_sold_date_sk = d_date_sk
      and ss_store_sk = s_store_sk
      and ss_hdemo_sk = hd_demo_sk
      and ss_addr_sk = ca_address_sk
      and d_dom between 1 and 2
      and (hd_dep_count = 4 or hd_vehicle_count = 3)
      and d_year in (1999, 2000, 2001)
      and s_city in ('Fairview', 'Midway')
    group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city
) dn, customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, ss_ticket_number
limit 100
"""

QUERIES["q72"] = """
select i_item_desc, w_warehouse_name, d1.d_week_seq,
       sum(case when p_promo_sk is null then 1 else 0 end) no_promo,
       sum(case when p_promo_sk is not null then 1 else 0 end) promo,
       count(*) total_cnt
from catalog_sales
join inventory on cs_item_sk = inv_item_sk
join warehouse on w_warehouse_sk = inv_warehouse_sk
join item on i_item_sk = cs_item_sk
join customer_demographics on cs_bill_cdemo_sk = cd_demo_sk
join household_demographics on cs_bill_hdemo_sk = hd_demo_sk
join date_dim d1 on cs_sold_date_sk = d1.d_date_sk
join date_dim d2 on inv_date_sk = d2.d_date_sk
join date_dim d3 on cs_ship_date_sk = d3.d_date_sk
left outer join promotion on cs_promo_sk = p_promo_sk
left outer join catalog_returns on cr_item_sk = cs_item_sk
  and cr_order_number = cs_order_number
where d1.d_week_seq = d2.d_week_seq
  and inv_quantity_on_hand < cs_quantity
  and d3.d_date > d1.d_date + 5
  and hd_buy_potential = '>10000'
  and d1.d_year = 1999
  and cd_marital_status = 'D'
group by i_item_desc, w_warehouse_name, d1.d_week_seq
order by 6 desc, 1, 2, 3
limit 100
"""

QUERIES["q95"] = """
with ws_wh as (
    select ws1.ws_order_number wh_order_number
    from web_sales ws1, web_sales ws2
    where ws1.ws_order_number = ws2.ws_order_number
      and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk
)
select count(distinct ws_order_number) as order_count,
       sum(ws_ext_ship_cost) as total_shipping_cost,
       sum(ws_net_profit) as total_net_profit
from web_sales, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and date '1999-04-02'
  and ws_ship_date_sk = d_date_sk
  and ws_ship_addr_sk = ca_address_sk
  and ca_state = 'IL'
  and ws_web_site_sk = web_site_sk
  and web_company_name = 'pri'
  and ws_order_number in (select wh_order_number from ws_wh)
  and ws_order_number in (
      select wr_order_number from web_returns, ws_wh
      where wr_order_number = wh_order_number
  )
"""

QUERIES["q96"] = """
select count(*)
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = t_time_sk
  and ss_hdemo_sk = hd_demo_sk
  and ss_store_sk = s_store_sk
  and t_hour = 20
  and t_minute >= 30
  and hd_dep_count = 7
  and s_store_name = 'ese'
"""

QUERIES["q98"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) as itemrevenue,
       sum(ss_ext_sales_price) * 100 / sum(sum(ss_ext_sales_price))
           over (partition by i_class) as revenueratio
from store_sales, item, date_dim
where ss_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ss_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-03-24'
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, 7
limit 100
"""

QUERIES["q37"] = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, catalog_sales
where i_current_price between 10 and 150
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-02-01' and date '2000-04-01'
  and i_manufact_id in (810, 872, 215, 901)
  and inv_quantity_on_hand between 100 and 500
  and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""

QUERIES["q82"] = """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, store_sales
where i_current_price between 10 and 150
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-05-25' and date '2000-07-24'
  and i_manufact_id in (990, 465, 354, 497)
  and inv_quantity_on_hand between 100 and 500
  and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
"""

QUERIES["q99"] = """
select w_warehouse_name, sm_type, cc_name,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk <= 30)
      then 1 else 0 end) as d30,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 30)
       and (cs_ship_date_sk - cs_sold_date_sk <= 60)
      then 1 else 0 end) as d60,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 60)
       and (cs_ship_date_sk - cs_sold_date_sk <= 90)
      then 1 else 0 end) as d90,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 90)
       and (cs_ship_date_sk - cs_sold_date_sk <= 120)
      then 1 else 0 end) as d120,
  sum(case when (cs_ship_date_sk - cs_sold_date_sk > 120)
      then 1 else 0 end) as dmore
from catalog_sales, warehouse, ship_mode, call_center, date_dim
where d_month_seq between 132 and 143
  and cs_ship_date_sk = d_date_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_ship_mode_sk = sm_ship_mode_sk
  and cs_call_center_sk = cc_call_center_sk
group by w_warehouse_name, sm_type, cc_name
order by 1, 2, 3
limit 100
"""


# ---- round-4 additions: rollup family + broad coverage (restated spec
# queries, parameters aligned to the generator calendar/domains) ----
QUERIES["q12"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ws_ext_sales_price) itemrevenue,
       sum(ws_ext_sales_price) * 100 / sum(sum(ws_ext_sales_price))
           over (partition by i_class) revenueratio
from web_sales, item, date_dim
where ws_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ws_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-02-22' + interval '30' day
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""
QUERIES["q15"] = """
select ca_zip, sum(cs_sales_price)
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and (substr(ca_zip, 1, 5) in ('85669','86197','88274','83405','86475',
                                '85392','85460','80348','81792')
       or ca_state in ('CA','WA','GA')
       or cs_sales_price > 500)
  and cs_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 2001
group by ca_zip
order by ca_zip
limit 100
"""
QUERIES["q18"] = """
select i_item_id, ca_country, ca_state, ca_county,
       avg(cast(cs_quantity as double)) agg1,
       avg(cast(cs_list_price as double)) agg2,
       avg(cast(cs_coupon_amt as double)) agg3,
       avg(cast(cs_sales_price as double)) agg4,
       avg(cast(cs_net_profit as double)) agg5,
       avg(cast(c_birth_year as double)) agg6,
       avg(cast(cd1.cd_dep_count as double)) agg7
from catalog_sales, customer_demographics cd1,
     customer_demographics cd2, customer, customer_address, date_dim, item
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd1.cd_demo_sk
  and cs_bill_customer_sk = c_customer_sk
  and cd1.cd_gender = 'F'
  and cd1.cd_education_status = 'Unknown'
  and c_current_cdemo_sk = cd2.cd_demo_sk
  and c_current_addr_sk = ca_address_sk
  and c_birth_month in (1, 6, 8, 9, 12, 2)
  and d_year = 1998
  and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA')
group by rollup(i_item_id, ca_country, ca_state, ca_county)
order by ca_country, ca_state, ca_county, i_item_id
limit 100
"""
QUERIES["q20"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(cs_ext_sales_price) itemrevenue,
       sum(cs_ext_sales_price) * 100 / sum(sum(cs_ext_sales_price))
           over (partition by i_class) revenueratio
from catalog_sales, item, date_dim
where cs_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and cs_sold_date_sk = d_date_sk
  and d_date between date '1999-02-22' and date '1999-02-22' + interval '30' day
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""
QUERIES["q22"] = """
select i_product_name, i_brand, i_class, i_category,
       avg(inv_quantity_on_hand) qoh
from inventory, date_dim, item
where inv_date_sk = d_date_sk
  and inv_item_sk = i_item_sk
  and d_month_seq between 108 and 119
group by rollup(i_product_name, i_brand, i_class, i_category)
order by qoh, i_product_name, i_brand, i_class, i_category
limit 100
"""
QUERIES["q26"] = """
select i_item_id, avg(cs_quantity) agg1, avg(cs_list_price) agg2,
       avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk
  and cs_promo_sk = p_promo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""
QUERIES["q27"] = """
select i_item_id, s_state, grouping(s_state) g_state,
       avg(ss_quantity) agg1, avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk
  and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and d_year = 2002
  and s_state in ('TN', 'TX', 'NE', 'MS')
group by rollup(i_item_id, s_state)
order by i_item_id, s_state
limit 100
"""
QUERIES["q34"] = """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, store, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and (date_dim.d_dom between 1 and 3 or date_dim.d_dom between 25 and 28)
        and (household_demographics.hd_buy_potential = '>10000'
             or household_demographics.hd_buy_potential = 'Unknown')
        and household_demographics.hd_vehicle_count > 0
        and (case when household_demographics.hd_vehicle_count > 0
             then cast(household_demographics.hd_dep_count as double)
                  / household_demographics.hd_vehicle_count
             else null end) > 1.2
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_county in ('Williamson County', 'Barrow County')
      group by ss_ticket_number, ss_customer_sk) dn, customer
where ss_customer_sk = c_customer_sk
  and cnt between 2 and 20
order by c_last_name, c_first_name, c_salutation,
         c_preferred_cust_flag desc, ss_ticket_number
"""
QUERIES["q36"] = """
select sum(ss_net_profit) / sum(ss_ext_sales_price) gross_margin,
       i_category, i_class,
       grouping(i_category) + grouping(i_class) lochierarchy,
       rank() over (partition by grouping(i_category) + grouping(i_class),
                    case when grouping(i_class) = 0 then i_category end
                    order by sum(ss_net_profit) / sum(ss_ext_sales_price))
           rank_within_parent
from store_sales, date_dim d1, item, store
where d1.d_year = 2001
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and s_state in ('TN', 'TX', 'NE', 'MS')
group by rollup(i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent, i_category, i_class
limit 100
"""
QUERIES["q43"] = """
select s_store_name, s_store_id,
       sum(case when d_day_name = 'Sunday' then ss_sales_price else null end) sun_sales,
       sum(case when d_day_name = 'Monday' then ss_sales_price else null end) mon_sales,
       sum(case when d_day_name = 'Tuesday' then ss_sales_price else null end) tue_sales,
       sum(case when d_day_name = 'Wednesday' then ss_sales_price else null end) wed_sales,
       sum(case when d_day_name = 'Thursday' then ss_sales_price else null end) thu_sales,
       sum(case when d_day_name = 'Friday' then ss_sales_price else null end) fri_sales,
       sum(case when d_day_name = 'Saturday' then ss_sales_price else null end) sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk
  and s_store_sk = ss_store_sk
  and s_gmt_offset > 0
  and d_year = 2000
group by s_store_name, s_store_id
order by s_store_name, s_store_id, sun_sales, mon_sales, tue_sales,
         wed_sales, thu_sales, fri_sales, sat_sales
limit 100
"""
QUERIES["q46"] = """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       amt, profit
from (select ss_ticket_number, ss_customer_sk, ca_city bought_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics,
           customer_address
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and store_sales.ss_addr_sk = customer_address.ca_address_sk
        and (household_demographics.hd_dep_count = 4
             or household_demographics.hd_vehicle_count = 3)
        and date_dim.d_dow in (6, 0)
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_city in ('Georgetown', 'Greenville', 'Union')
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and customer.c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number
limit 100
"""
QUERIES["q53"] = """
select * from (
  select i_manufact_id, sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) over (partition by i_manufact_id)
             avg_quarterly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_month_seq between 108 and 119
    and ((i_category in ('Books', 'Children', 'Electronics')
          and i_class in ('fiction', 'kids', 'computers'))
         or (i_category in ('Women', 'Music', 'Men')
             and i_class in ('accessories', 'classical', 'pants')))
  group by i_manufact_id, d_qoy) tmp1
where case when avg_quarterly_sales > 0
      then abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
      else null end > 0.1
order by avg_quarterly_sales, sum_sales, i_manufact_id
limit 100
"""
QUERIES["q63"] = """
select * from (
  select i_manager_id, sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price)) over (partition by i_manager_id)
             avg_monthly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_month_seq between 108 and 119
    and ((i_category in ('Books', 'Children', 'Electronics')
          and i_class in ('fiction', 'kids', 'computers'))
         or (i_category in ('Women', 'Music', 'Men')
             and i_class in ('accessories', 'classical', 'pants')))
  group by i_manager_id, d_moy) tmp1
where case when avg_monthly_sales > 0
      then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
      else null end > 0.1
order by i_manager_id, avg_monthly_sales, sum_sales
limit 100
"""
QUERIES["q65"] = """
select s_store_name, i_item_desc, sc.revenue, i_current_price,
       i_wholesale_cost, i_brand
from store, item,
     (select ss_store_sk, avg(revenue) ave
      from (select ss_store_sk, ss_item_sk, sum(ss_sales_price) revenue
            from store_sales, date_dim
            where ss_sold_date_sk = d_date_sk
              and d_month_seq between 108 and 119
            group by ss_store_sk, ss_item_sk) sa
      group by ss_store_sk) sb,
     (select ss_store_sk, ss_item_sk, sum(ss_sales_price) revenue
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk
        and d_month_seq between 108 and 119
      group by ss_store_sk, ss_item_sk) sc
where sb.ss_store_sk = sc.ss_store_sk
  and sc.revenue <= 0.1 * sb.ave
  and s_store_sk = sc.ss_store_sk
  and i_item_sk = sc.ss_item_sk
order by s_store_name, i_item_desc, i_brand, sc.revenue
limit 100
"""
QUERIES["q73"] = """
select c_last_name, c_first_name, c_salutation, c_preferred_cust_flag,
       ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, store, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and date_dim.d_dom between 1 and 2
        and (household_demographics.hd_buy_potential = '>10000'
             or household_demographics.hd_buy_potential = 'Unknown')
        and household_demographics.hd_vehicle_count > 0
        and (case when household_demographics.hd_vehicle_count > 0
             then cast(household_demographics.hd_dep_count as double)
                  / household_demographics.hd_vehicle_count
             else null end) > 1
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_county in ('Williamson County', 'Furnas County')
      group by ss_ticket_number, ss_customer_sk) dj, customer
where ss_customer_sk = c_customer_sk
  and cnt between 1 and 5
order by cnt desc, c_last_name, ss_ticket_number
"""
QUERIES["q79"] = """
select c_last_name, c_first_name, substr(s_city, 1, 30), ss_ticket_number,
       amt, profit
from (select ss_ticket_number, ss_customer_sk, store.s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and (household_demographics.hd_dep_count = 6
             or household_demographics.hd_vehicle_count > 2)
        and date_dim.d_dow = 1
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_number_employees between 40 and 400
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, store.s_city) ms,
     customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, substr(s_city, 1, 30), profit
limit 100
"""
QUERIES["q86"] = """
select sum(ws_net_paid) total_sum, i_category, i_class,
       grouping(i_category) + grouping(i_class) lochierarchy,
       rank() over (partition by grouping(i_category) + grouping(i_class),
                    case when grouping(i_class) = 0 then i_category end
                    order by sum(ws_net_paid) desc) rank_within_parent
from web_sales, date_dim d1, item
where d1.d_month_seq between 108 and 119
  and d1.d_date_sk = ws_sold_date_sk
  and i_item_sk = ws_item_sk
group by rollup(i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent, i_category, i_class
limit 100
"""
QUERIES["q88"] = """
select * from
 (select count(*) h8_30_to_9 from store_sales, household_demographics,
         time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 8 and time_dim.t_minute >= 30
    and ((household_demographics.hd_dep_count = 4
          and household_demographics.hd_vehicle_count <= 6)
         or (household_demographics.hd_dep_count = 2
             and household_demographics.hd_vehicle_count <= 4)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2))
    and store.s_store_name = 'ese') s1,
 (select count(*) h9_to_9_30 from store_sales, household_demographics,
         time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 9 and time_dim.t_minute < 30
    and ((household_demographics.hd_dep_count = 4
          and household_demographics.hd_vehicle_count <= 6)
         or (household_demographics.hd_dep_count = 2
             and household_demographics.hd_vehicle_count <= 4)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2))
    and store.s_store_name = 'ese') s2,
 (select count(*) h9_30_to_10 from store_sales, household_demographics,
         time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 9 and time_dim.t_minute >= 30
    and ((household_demographics.hd_dep_count = 4
          and household_demographics.hd_vehicle_count <= 6)
         or (household_demographics.hd_dep_count = 2
             and household_demographics.hd_vehicle_count <= 4)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2))
    and store.s_store_name = 'ese') s3,
 (select count(*) h10_to_10_30 from store_sales, household_demographics,
         time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 10 and time_dim.t_minute < 30
    and ((household_demographics.hd_dep_count = 4
          and household_demographics.hd_vehicle_count <= 6)
         or (household_demographics.hd_dep_count = 2
             and household_demographics.hd_vehicle_count <= 4)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2))
    and store.s_store_name = 'ese') s4
"""
QUERIES["q89"] = """
select * from (
  select i_category, i_class, i_brand, s_store_name, s_company_name,
         d_moy, sum(ss_sales_price) sum_sales,
         avg(cast(sum(ss_sales_price) as double)) over (partition by
             i_category, i_brand, s_store_name, s_company_name)
             avg_monthly_sales
  from item, store_sales, date_dim, store
  where ss_item_sk = i_item_sk
    and ss_sold_date_sk = d_date_sk
    and ss_store_sk = s_store_sk
    and d_year in (1999)
    and ((i_category in ('Books', 'Electronics', 'Sports')
          and i_class in ('computers', 'shirts', 'baseball'))
         or (i_category in ('Men', 'Jewelry', 'Women')
             and i_class in ('accessories', 'dresses', 'pants')))
  group by i_category, i_class, i_brand, s_store_name, s_company_name,
           d_moy) tmp1
where case when avg_monthly_sales <> 0
      then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
      else null end > 0.1
order by sum_sales - avg_monthly_sales, s_store_name, i_category,
         i_class, i_brand, d_moy
limit 100
"""
QUERIES["q93"] = """
select ss_customer_sk, sum(act_sales) sumsales
from (select ss_item_sk, ss_ticket_number, ss_customer_sk,
             case when sr_return_quantity is not null
                  then (ss_quantity - sr_return_quantity) * ss_sales_price
                  else ss_quantity * ss_sales_price end act_sales
      from store_sales left join store_returns
           on sr_item_sk = ss_item_sk and sr_ticket_number = ss_ticket_number,
           reason
      where sr_reason_sk = r_reason_sk
        and r_reason_desc = 'Package was damaged') t
group by ss_customer_sk
order by sumsales, ss_customer_sk
limit 100
"""
QUERIES["q97"] = """
with ssci as (
  select ss_customer_sk customer_sk, ss_item_sk item_sk
  from store_sales, date_dim
  where ss_sold_date_sk = d_date_sk
    and d_month_seq between 108 and 119
  group by ss_customer_sk, ss_item_sk),
csci as (
  select cs_bill_customer_sk customer_sk, cs_item_sk item_sk
  from catalog_sales, date_dim
  where cs_sold_date_sk = d_date_sk
    and d_month_seq between 108 and 119
  group by cs_bill_customer_sk, cs_item_sk)
select sum(case when ssci.customer_sk is not null
                 and csci.customer_sk is null then 1 else 0 end) store_only,
       sum(case when ssci.customer_sk is null
                 and csci.customer_sk is not null then 1 else 0 end) catalog_only,
       sum(case when ssci.customer_sk is not null
                 and csci.customer_sk is not null then 1 else 0 end) store_and_catalog
from ssci full outer join csci
     on ssci.customer_sk = csci.customer_sk and ssci.item_sk = csci.item_sk
limit 100
"""

#: sqlite-oracle equivalents for queries sqlite cannot run
#: directly (ROLLUP/GROUPING spelled as explicit UNION ALLs;
#: ordering adds NULLS LAST to match engine null ordering)
SQLITE_ORACLE: dict[str, str] = {}
SQLITE_ORACLE["q18"] = """
select i_item_id, ca_country, ca_state, ca_county, avg(1.0*cs_quantity),
       avg(1.0*cs_list_price), avg(1.0*cs_coupon_amt),
       avg(1.0*cs_sales_price), avg(1.0*cs_net_profit),
       avg(1.0*c_birth_year), avg(1.0*cd_dep_count)
from (select cs_quantity, cs_list_price, cs_coupon_amt, cs_sales_price,
             cs_net_profit, c_birth_year, cd1.cd_dep_count, i_item_id,
             ca_country, ca_state, ca_county
      from catalog_sales, customer_demographics cd1,
           customer_demographics cd2, customer, customer_address,
           date_dim, item
      where cs_sold_date_sk = d_date_sk
        and cs_item_sk = i_item_sk
        and cs_bill_cdemo_sk = cd1.cd_demo_sk
        and cs_bill_customer_sk = c_customer_sk
        and cd1.cd_gender = 'F'
        and cd1.cd_education_status = 'Unknown'
        and c_current_cdemo_sk = cd2.cd_demo_sk
        and c_current_addr_sk = ca_address_sk
        and c_birth_month in (1, 6, 8, 9, 12, 2)
        and d_year = 1998
        and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA'))
group by i_item_id, ca_country, ca_state, ca_county
union all
select i_item_id, ca_country, ca_state, null, avg(1.0*cs_quantity),
       avg(1.0*cs_list_price), avg(1.0*cs_coupon_amt),
       avg(1.0*cs_sales_price), avg(1.0*cs_net_profit),
       avg(1.0*c_birth_year), avg(1.0*cd_dep_count)
from (select cs_quantity, cs_list_price, cs_coupon_amt, cs_sales_price,
             cs_net_profit, c_birth_year, cd1.cd_dep_count, i_item_id,
             ca_country, ca_state
      from catalog_sales, customer_demographics cd1,
           customer_demographics cd2, customer, customer_address,
           date_dim, item
      where cs_sold_date_sk = d_date_sk
        and cs_item_sk = i_item_sk
        and cs_bill_cdemo_sk = cd1.cd_demo_sk
        and cs_bill_customer_sk = c_customer_sk
        and cd1.cd_gender = 'F'
        and cd1.cd_education_status = 'Unknown'
        and c_current_cdemo_sk = cd2.cd_demo_sk
        and c_current_addr_sk = ca_address_sk
        and c_birth_month in (1, 6, 8, 9, 12, 2)
        and d_year = 1998
        and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA'))
group by i_item_id, ca_country, ca_state
union all
select i_item_id, ca_country, null, null, avg(1.0*cs_quantity),
       avg(1.0*cs_list_price), avg(1.0*cs_coupon_amt),
       avg(1.0*cs_sales_price), avg(1.0*cs_net_profit),
       avg(1.0*c_birth_year), avg(1.0*cd_dep_count)
from (select cs_quantity, cs_list_price, cs_coupon_amt, cs_sales_price,
             cs_net_profit, c_birth_year, cd1.cd_dep_count, i_item_id,
             ca_country
      from catalog_sales, customer_demographics cd1,
           customer_demographics cd2, customer, customer_address,
           date_dim, item
      where cs_sold_date_sk = d_date_sk
        and cs_item_sk = i_item_sk
        and cs_bill_cdemo_sk = cd1.cd_demo_sk
        and cs_bill_customer_sk = c_customer_sk
        and cd1.cd_gender = 'F'
        and cd1.cd_education_status = 'Unknown'
        and c_current_cdemo_sk = cd2.cd_demo_sk
        and c_current_addr_sk = ca_address_sk
        and c_birth_month in (1, 6, 8, 9, 12, 2)
        and d_year = 1998
        and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA'))
group by i_item_id, ca_country
union all
select i_item_id, null, null, null, avg(1.0*cs_quantity),
       avg(1.0*cs_list_price), avg(1.0*cs_coupon_amt),
       avg(1.0*cs_sales_price), avg(1.0*cs_net_profit),
       avg(1.0*c_birth_year), avg(1.0*cd_dep_count)
from (select cs_quantity, cs_list_price, cs_coupon_amt, cs_sales_price,
             cs_net_profit, c_birth_year, cd1.cd_dep_count, i_item_id
      from catalog_sales, customer_demographics cd1,
           customer_demographics cd2, customer, customer_address,
           date_dim, item
      where cs_sold_date_sk = d_date_sk
        and cs_item_sk = i_item_sk
        and cs_bill_cdemo_sk = cd1.cd_demo_sk
        and cs_bill_customer_sk = c_customer_sk
        and cd1.cd_gender = 'F'
        and cd1.cd_education_status = 'Unknown'
        and c_current_cdemo_sk = cd2.cd_demo_sk
        and c_current_addr_sk = ca_address_sk
        and c_birth_month in (1, 6, 8, 9, 12, 2)
        and d_year = 1998
        and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA'))
group by i_item_id
union all
select null, null, null, null, avg(1.0*cs_quantity),
       avg(1.0*cs_list_price), avg(1.0*cs_coupon_amt),
       avg(1.0*cs_sales_price), avg(1.0*cs_net_profit),
       avg(1.0*c_birth_year), avg(1.0*cd_dep_count)
from (select cs_quantity, cs_list_price, cs_coupon_amt, cs_sales_price,
             cs_net_profit, c_birth_year, cd1.cd_dep_count
      from catalog_sales, customer_demographics cd1,
           customer_demographics cd2, customer, customer_address,
           date_dim, item
      where cs_sold_date_sk = d_date_sk
        and cs_item_sk = i_item_sk
        and cs_bill_cdemo_sk = cd1.cd_demo_sk
        and cs_bill_customer_sk = c_customer_sk
        and cd1.cd_gender = 'F'
        and cd1.cd_education_status = 'Unknown'
        and c_current_cdemo_sk = cd2.cd_demo_sk
        and c_current_addr_sk = ca_address_sk
        and c_birth_month in (1, 6, 8, 9, 12, 2)
        and d_year = 1998
        and ca_state in ('MS', 'IN', 'ND', 'OK', 'NM', 'VA'))
order by 2, 3, 4, 1
limit 100
"""
SQLITE_ORACLE["q22"] = """
select i_product_name, i_brand, i_class, i_category,
       avg(1.0*inv_quantity_on_hand) qoh
from inventory, date_dim, item
where inv_date_sk = d_date_sk and inv_item_sk = i_item_sk
  and d_month_seq between 108 and 119
group by i_product_name, i_brand, i_class, i_category
union all
select i_product_name, i_brand, i_class, null, avg(1.0*inv_quantity_on_hand)
from inventory, date_dim, item
where inv_date_sk = d_date_sk and inv_item_sk = i_item_sk
  and d_month_seq between 108 and 119
group by i_product_name, i_brand, i_class
union all
select i_product_name, i_brand, null, null, avg(1.0*inv_quantity_on_hand)
from inventory, date_dim, item
where inv_date_sk = d_date_sk and inv_item_sk = i_item_sk
  and d_month_seq between 108 and 119
group by i_product_name, i_brand
union all
select i_product_name, null, null, null, avg(1.0*inv_quantity_on_hand)
from inventory, date_dim, item
where inv_date_sk = d_date_sk and inv_item_sk = i_item_sk
  and d_month_seq between 108 and 119
group by i_product_name
union all
select null, null, null, null, avg(1.0*inv_quantity_on_hand)
from inventory, date_dim, item
where inv_date_sk = d_date_sk and inv_item_sk = i_item_sk
  and d_month_seq between 108 and 119
order by 5, 1 nulls last, 2 nulls last, 3 nulls last, 4 nulls last
limit 100
"""
SQLITE_ORACLE["q27"] = """
select i_item_id, s_state, 0, avg(1.0*ss_quantity), avg(1.0*ss_list_price),
       avg(1.0*ss_coupon_amt), avg(1.0*ss_sales_price)
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College' and d_year = 2002
  and s_state in ('TN', 'TX', 'NE', 'MS')
group by i_item_id, s_state
union all
select i_item_id, null, 1, avg(1.0*ss_quantity), avg(1.0*ss_list_price),
       avg(1.0*ss_coupon_amt), avg(1.0*ss_sales_price)
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College' and d_year = 2002
  and s_state in ('TN', 'TX', 'NE', 'MS')
group by i_item_id
union all
select null, null, 1, avg(1.0*ss_quantity), avg(1.0*ss_list_price),
       avg(1.0*ss_coupon_amt), avg(1.0*ss_sales_price)
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'M' and cd_marital_status = 'S'
  and cd_education_status = 'College' and d_year = 2002
  and s_state in ('TN', 'TX', 'NE', 'MS')
order by 1 nulls last, 2 nulls last
limit 100
"""
SQLITE_ORACLE["q36"] = """
select gross_margin, i_category, i_class, lochierarchy,
       rank() over (partition by lochierarchy,
                    case when lochierarchy = 0 then i_category end
                    order by gross_margin) rank_within_parent
from (
  select 1.0*sum(ss_net_profit) / sum(ss_ext_sales_price) gross_margin,
         i_category, i_class, 0 lochierarchy
  from store_sales, date_dim d1, item, store
  where d1.d_year = 2001 and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
    and s_state in ('TN', 'TX', 'NE', 'MS')
  group by i_category, i_class
  union all
  select 1.0*sum(ss_net_profit) / sum(ss_ext_sales_price), i_category,
         null, 1
  from store_sales, date_dim d1, item, store
  where d1.d_year = 2001 and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
    and s_state in ('TN', 'TX', 'NE', 'MS')
  group by i_category
  union all
  select 1.0*sum(ss_net_profit) / sum(ss_ext_sales_price), null, null, 2
  from store_sales, date_dim d1, item, store
  where d1.d_year = 2001 and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk and s_store_sk = ss_store_sk
    and s_state in ('TN', 'TX', 'NE', 'MS'))
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent, i_category, i_class
limit 100
"""
SQLITE_ORACLE["q86"] = """
select total_sum, i_category, i_class, lochierarchy,
       rank() over (partition by lochierarchy,
                    case when lochierarchy = 0 then i_category end
                    order by total_sum desc) rank_within_parent
from (
  select sum(ws_net_paid) total_sum, i_category, i_class, 0 lochierarchy
  from web_sales, date_dim d1, item
  where d1.d_month_seq between 108 and 119
    and d1.d_date_sk = ws_sold_date_sk and i_item_sk = ws_item_sk
  group by i_category, i_class
  union all
  select sum(ws_net_paid), i_category, null, 1
  from web_sales, date_dim d1, item
  where d1.d_month_seq between 108 and 119
    and d1.d_date_sk = ws_sold_date_sk and i_item_sk = ws_item_sk
  group by i_category
  union all
  select sum(ws_net_paid), null, null, 2
  from web_sales, date_dim d1, item
  where d1.d_month_seq between 108 and 119
    and d1.d_date_sk = ws_sold_date_sk and i_item_sk = ws_item_sk)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent, i_category, i_class
limit 100
"""

QUERIES["q13"] = """
select avg(ss_quantity), avg(ss_ext_sales_price),
       avg(ss_ext_wholesale_cost), sum(ss_ext_wholesale_cost)
from store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk
  and d_year = 2001
  and ((ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'M'
        and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 100.00 and 150.00
        and hd_dep_count = 3)
       or (ss_hdemo_sk = hd_demo_sk
           and cd_demo_sk = ss_cdemo_sk
           and cd_marital_status = 'S'
           and cd_education_status = 'College'
           and ss_sales_price between 50.00 and 100.00
           and hd_dep_count = 1)
       or (ss_hdemo_sk = hd_demo_sk
           and cd_demo_sk = ss_cdemo_sk
           and cd_marital_status = 'W'
           and cd_education_status = '2 yr Degree'
           and ss_sales_price between 150.00 and 200.00
           and hd_dep_count = 1))
  and ((ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('TX', 'OH', 'KS')
        and ss_net_profit between 100 and 200)
       or (ss_addr_sk = ca_address_sk
           and ca_country = 'United States'
           and ca_state in ('OR', 'NE', 'KY')
           and ss_net_profit between 150 and 300)
       or (ss_addr_sk = ca_address_sk
           and ca_country = 'United States'
           and ca_state in ('VA', 'TN', 'MS')
           and ss_net_profit between 50 and 250))
"""

QUERIES["q48"] = """
select sum(ss_quantity)
from store_sales, store, customer_demographics, customer_address,
     date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk
  and d_year = 2000
  and ((cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'M'
        and cd_education_status = '4 yr Degree'
        and ss_sales_price between 100.00 and 150.00)
       or (cd_demo_sk = ss_cdemo_sk
           and cd_marital_status = 'D'
           and cd_education_status = '2 yr Degree'
           and ss_sales_price between 50.00 and 100.00)
       or (cd_demo_sk = ss_cdemo_sk
           and cd_marital_status = 'S'
           and cd_education_status = 'College'
           and ss_sales_price between 150.00 and 200.00))
  and ((ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('CO', 'OH', 'TX')
        and ss_net_profit between 0 and 2000)
       or (ss_addr_sk = ca_address_sk
           and ca_country = 'United States'
           and ca_state in ('OR', 'MN', 'KY')
           and ss_net_profit between 150 and 3000)
       or (ss_addr_sk = ca_address_sk
           and ca_country = 'United States'
           and ca_state in ('VA', 'CA', 'MS')
           and ss_net_profit between 50 and 25000))
"""

# q13/q48: sqlite cannot plan the spec's OR-embedded join conditions
# (it cross-joins and never finishes even at tiny); the oracle text is
# the factored-equivalent form — the same rewrite the engine's
# optimizer applies (ExtractCommonPredicates analog)
SQLITE_ORACLE["q13"] = """
select avg(ss_quantity), avg(ss_ext_sales_price),
       avg(ss_ext_wholesale_cost), sum(ss_ext_wholesale_cost)
from store_sales, store, customer_demographics,
     household_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk
  and d_year = 2001
  and ss_hdemo_sk = hd_demo_sk
  and cd_demo_sk = ss_cdemo_sk
  and ss_addr_sk = ca_address_sk
  and ca_country = 'United States'
  and ((cd_marital_status = 'M'
        and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 100.00 and 150.00
        and hd_dep_count = 3)
       or (cd_marital_status = 'S'
           and cd_education_status = 'College'
           and ss_sales_price between 50.00 and 100.00
           and hd_dep_count = 1)
       or (cd_marital_status = 'W'
           and cd_education_status = '2 yr Degree'
           and ss_sales_price between 150.00 and 200.00
           and hd_dep_count = 1))
  and ((ca_state in ('TX', 'OH', 'KS')
        and ss_net_profit between 100 and 200)
       or (ca_state in ('OR', 'NE', 'KY')
           and ss_net_profit between 150 and 300)
       or (ca_state in ('VA', 'TN', 'MS')
           and ss_net_profit between 50 and 250))
"""

SQLITE_ORACLE["q48"] = """
select sum(ss_quantity)
from store_sales, store, customer_demographics, customer_address,
     date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk
  and d_year = 2000
  and cd_demo_sk = ss_cdemo_sk
  and ss_addr_sk = ca_address_sk
  and ca_country = 'United States'
  and ((cd_marital_status = 'M'
        and cd_education_status = '4 yr Degree'
        and ss_sales_price between 100.00 and 150.00)
       or (cd_marital_status = 'D'
           and cd_education_status = '2 yr Degree'
           and ss_sales_price between 50.00 and 100.00)
       or (cd_marital_status = 'S'
           and cd_education_status = 'College'
           and ss_sales_price between 150.00 and 200.00))
  and ((ca_state in ('CO', 'OH', 'TX')
        and ss_net_profit between 0 and 2000)
       or (ca_state in ('OR', 'MN', 'KY')
           and ss_net_profit between 150 and 3000)
       or (ca_state in ('VA', 'CA', 'MS')
           and ss_net_profit between 50 and 25000))
"""


def _rollup_union(keys, aggs, body):
    """sqlite oracle helper: spell GROUP BY ROLLUP(keys) as the union
    of its grouping sets (sqlite has no ROLLUP)."""
    branches = []
    for i in range(len(keys), -1, -1):
        cols = keys[:i] + ["null"] * (len(keys) - i)
        group = f"group by {', '.join(keys[:i])}" if i else ""
        branches.append(
            f"select {', '.join(cols)}, {aggs} {body} {group}"
        )
    return " union all ".join(branches)


QUERIES["q67"] = """
select * from (
  select i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, s_store_id, sumsales,
         rank() over (partition by i_category
                      order by sumsales desc) rk
  from (select i_category, i_class, i_brand, i_product_name, d_year,
               d_qoy, d_moy, s_store_id,
               sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales
        from store_sales, date_dim, store, item
        where ss_sold_date_sk = d_date_sk
          and ss_item_sk = i_item_sk
          and ss_store_sk = s_store_sk
          and d_month_seq between 108 and 119
        group by rollup(i_category, i_class, i_brand, i_product_name,
                        d_year, d_qoy, d_moy, s_store_id)) dw1) dw2
where rk <= 100
order by i_category, i_class, i_brand, i_product_name, d_year, d_qoy,
         d_moy, s_store_id, sumsales, rk
limit 100
"""

SQLITE_ORACLE["q67"] = (
    "select * from (select i_category, i_class, i_brand, "
    "i_product_name, d_year, d_qoy, d_moy, s_store_id, sumsales, "
    "rank() over (partition by i_category order by sumsales desc) rk "
    "from ("
    + _rollup_union(
        ["i_category", "i_class", "i_brand", "i_product_name",
         "d_year", "d_qoy", "d_moy", "s_store_id"],
        "sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales",
        "from store_sales, date_dim, store, item "
        "where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk "
        "and ss_store_sk = s_store_sk "
        "and d_month_seq between 108 and 119",
    )
    + ") dw1) dw2 where rk <= 100 "
    "order by i_category nulls last, i_class nulls last, "
    "i_brand nulls last, i_product_name nulls last, "
    "d_year nulls last, d_qoy nulls last, d_moy nulls last, "
    "s_store_id nulls last, sumsales, rk limit 100"
)

_Q80_CHANNELS = """
   select 'store channel' channel, 'store' || store_id id, sales,
          returns, profit
   from (select s_store_id store_id, sum(ss_ext_sales_price) sales,
                sum(coalesce(sr_return_amt, 0)) returns,
                sum(ss_net_profit - coalesce(sr_net_loss, 0)) profit
         from store_sales left join store_returns
              on ss_item_sk = sr_item_sk
              and ss_ticket_number = sr_ticket_number,
              date_dim, store, item, promotion
         where ss_sold_date_sk = d_date_sk
           and d_date between date '2000-08-23'
               and date '2000-08-23' + interval '30' day
           and ss_store_sk = s_store_sk
           and ss_item_sk = i_item_sk
           and i_current_price > 50
           and ss_promo_sk = p_promo_sk
           and p_channel_tv = 'N'
         group by s_store_id) ssr
   union all
   select 'catalog channel', 'catalog_page' || catalog_page_id, sales,
          returns, profit
   from (select cp_catalog_page_id catalog_page_id,
                sum(cs_ext_sales_price) sales,
                sum(coalesce(cr_return_amount, 0)) returns,
                sum(cs_net_profit - coalesce(cr_net_loss, 0)) profit
         from catalog_sales left join catalog_returns
              on cs_item_sk = cr_item_sk
              and cs_order_number = cr_order_number,
              date_dim, catalog_page, item, promotion
         where cs_sold_date_sk = d_date_sk
           and d_date between date '2000-08-23'
               and date '2000-08-23' + interval '30' day
           and cs_catalog_page_sk = cp_catalog_page_sk
           and cs_item_sk = i_item_sk
           and i_current_price > 50
           and cs_promo_sk = p_promo_sk
           and p_channel_tv = 'N'
         group by cp_catalog_page_id) csr
   union all
   select 'web channel', 'web_site' || web_id, sales, returns, profit
   from (select web_site_id web_id, sum(ws_ext_sales_price) sales,
                sum(coalesce(wr_return_amt, 0)) returns,
                sum(ws_net_profit - coalesce(wr_net_loss, 0)) profit
         from web_sales left join web_returns
              on ws_item_sk = wr_item_sk
              and ws_order_number = wr_order_number,
              date_dim, web_site, item, promotion
         where ws_sold_date_sk = d_date_sk
           and d_date between date '2000-08-23'
               and date '2000-08-23' + interval '30' day
           and ws_web_site_sk = web_site_sk
           and ws_item_sk = i_item_sk
           and i_current_price > 50
           and ws_promo_sk = p_promo_sk
           and p_channel_tv = 'N'
         group by web_site_id) wsr
"""

QUERIES["q80"] = f"""
select channel, id, sum(sales) sales, sum(returns) returns,
       sum(profit) profit
from ({_Q80_CHANNELS}) x
group by rollup(channel, id)
order by channel, id
limit 100
"""

SQLITE_ORACLE["q80"] = (
    _rollup_union(
        ["channel", "id"],
        "sum(sales) sales, sum(returns) returns, sum(profit) profit",
        f"from ({_Q80_CHANNELS}) x",
    )
    + " order by 1 nulls last, 2 nulls last limit 100"
)

_Q77_BODY = """
with ss as (
  select s_store_sk, sum(ss_ext_sales_price) sales,
         sum(ss_net_profit) profit
  from store_sales, date_dim, store
  where ss_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '30' day
    and ss_store_sk = s_store_sk
  group by s_store_sk),
sr as (
  select sr_store_sk s_store_sk, sum(sr_return_amt) returns,
         sum(sr_net_loss) profit_loss
  from store_returns, date_dim, store
  where sr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '30' day
    and sr_store_sk = s_store_sk
  group by sr_store_sk),
cs as (
  select cs_call_center_sk, sum(cs_ext_sales_price) sales,
         sum(cs_net_profit) profit
  from catalog_sales, date_dim
  where cs_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '30' day
  group by cs_call_center_sk),
cr as (
  select cr_call_center_sk, sum(cr_return_amount) returns,
         sum(cr_net_loss) profit_loss
  from catalog_returns, date_dim
  where cr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '30' day
  group by cr_call_center_sk),
ws as (
  select wp_web_page_sk, sum(ws_ext_sales_price) sales,
         sum(ws_net_profit) profit
  from web_sales, date_dim, web_page
  where ws_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '30' day
    and ws_web_page_sk = wp_web_page_sk
  group by wp_web_page_sk),
wr as (
  select wr_web_page_sk wp_web_page_sk, sum(wr_return_amt) returns,
         sum(wr_net_loss) profit_loss
  from web_returns, date_dim, web_page
  where wr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '30' day
    and wr_web_page_sk = wp_web_page_sk
  group by wr_web_page_sk)
"""

_Q77_UNION = """
   select 'store channel' channel, ss.s_store_sk id, sales,
          coalesce(returns, 0) returns,
          profit - coalesce(profit_loss, 0) profit
   from ss left join sr on ss.s_store_sk = sr.s_store_sk
   union all
   select 'catalog channel', cs_call_center_sk, sales, returns,
          profit - profit_loss
   from cs, cr
   union all
   select 'web channel', ws.wp_web_page_sk, sales,
          coalesce(returns, 0) returns,
          profit - coalesce(profit_loss, 0) profit
   from ws left join wr on ws.wp_web_page_sk = wr.wp_web_page_sk
"""

QUERIES["q77"] = f"""
{_Q77_BODY}
select channel, id, sum(sales) sales, sum(returns) returns,
       sum(profit) profit
from ({_Q77_UNION}) x
group by rollup(channel, id)
order by channel, id, sales
limit 100
"""

SQLITE_ORACLE["q77"] = (
    _Q77_BODY
    + _rollup_union(
        ["channel", "id"],
        "sum(sales) sales, sum(returns) returns, sum(profit) profit",
        f"from ({_Q77_UNION}) x",
    )
    + " order by 1 nulls last, 2 nulls last, 3 limit 100"
)

_Q5_BODY = """
with ssr as (
  select s_store_id, sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns, sum(net_loss) profit_loss
  from (select ss_store_sk store_sk, ss_sold_date_sk date_sk,
               ss_ext_sales_price sales_price, ss_net_profit profit,
               cast(0 as decimal(7,2)) return_amt,
               cast(0 as decimal(7,2)) net_loss
        from store_sales
        union all
        select sr_store_sk, sr_returned_date_sk,
               cast(0 as decimal(7,2)), cast(0 as decimal(7,2)),
               sr_return_amt, sr_net_loss
        from store_returns) salesreturns, date_dim, store
  where date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '14' day
    and store_sk = s_store_sk
  group by s_store_id),
csr as (
  select cp_catalog_page_id, sum(sales_price) sales,
         sum(profit) profit, sum(return_amt) returns,
         sum(net_loss) profit_loss
  from (select cs_catalog_page_sk page_sk, cs_sold_date_sk date_sk,
               cs_ext_sales_price sales_price, cs_net_profit profit,
               cast(0 as decimal(7,2)) return_amt,
               cast(0 as decimal(7,2)) net_loss
        from catalog_sales
        union all
        select cr_catalog_page_sk, cr_returned_date_sk,
               cast(0 as decimal(7,2)), cast(0 as decimal(7,2)),
               cr_return_amount, cr_net_loss
        from catalog_returns) salesreturns, date_dim, catalog_page
  where date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '14' day
    and page_sk = cp_catalog_page_sk
  group by cp_catalog_page_id),
wsr as (
  select web_site_id, sum(sales_price) sales, sum(profit) profit,
         sum(return_amt) returns, sum(net_loss) profit_loss
  from (select ws_web_site_sk wsr_web_site_sk, ws_sold_date_sk date_sk,
               ws_ext_sales_price sales_price, ws_net_profit profit,
               cast(0 as decimal(7,2)) return_amt,
               cast(0 as decimal(7,2)) net_loss
        from web_sales
        union all
        select ws_web_site_sk, wr_returned_date_sk,
               cast(0 as decimal(7,2)), cast(0 as decimal(7,2)),
               wr_return_amt, wr_net_loss
        from web_returns left join web_sales
             on wr_item_sk = ws_item_sk
             and wr_order_number = ws_order_number) salesreturns,
       date_dim, web_site
  where date_sk = d_date_sk
    and d_date between date '2000-08-23'
        and date '2000-08-23' + interval '14' day
    and wsr_web_site_sk = web_site_sk
  group by web_site_id)
"""

_Q5_UNION = """
   select 'store channel' channel, 'store' || s_store_id id, sales,
          returns, profit - profit_loss profit
   from ssr
   union all
   select 'catalog channel', 'catalog_page' || cp_catalog_page_id,
          sales, returns, profit - profit_loss
   from csr
   union all
   select 'web channel', 'web_site' || web_site_id, sales, returns,
          profit - profit_loss
   from wsr
"""

QUERIES["q5"] = f"""
{_Q5_BODY}
select channel, id, sum(sales) sales, sum(returns) returns,
       sum(profit) profit
from ({_Q5_UNION}) x
group by rollup(channel, id)
order by channel, id
limit 100
"""

SQLITE_ORACLE["q5"] = (
    _Q5_BODY
    + _rollup_union(
        ["channel", "id"],
        "sum(sales) sales, sum(returns) returns, sum(profit) profit",
        f"from ({_Q5_UNION}) x",
    )
    + " order by 1 nulls last, 2 nulls last limit 100"
)
