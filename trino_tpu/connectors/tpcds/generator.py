"""Deterministic, vectorized TPC-DS data generation.

The analog of the reference's TPC-DS generator connector
(plugin/trino-tpcds/, backed by the teradata tpcds library): the full
24-table TPC-DS schema (spec column names and types), generated as
numpy columns with the spec's structural rules — a real calendar
date_dim, surrogate-key dimensions, multi-line sales "documents"
(ticket/order numbers repeat across rows), returns drawn as subsets of
sales, weekly inventory snapshots, and internally consistent derived
pricing columns.

Not bit-identical to dsdgen's RNG streams (like tpch/generator.py is
not bit-identical to dbgen) — correctness tests load THIS data into
sqlite, so engine results are checked against golden results over
identical inputs. Columns generate on demand per (table, column) and
cache in memory; tiny scale is sized for tests.
"""

from __future__ import annotations

import numpy as np

from trino_tpu import types as T
from trino_tpu.connectors.base import TableSchema
from trino_tpu.types import parse_date

__all__ = ["TpcdsData", "SCHEMAS", "SCHEMA_SF"]

D52 = T.DecimalType(5, 2)
D72 = T.DecimalType(7, 2)
D152 = T.DecimalType(15, 2)
I = T.INTEGER
B = T.BIGINT
V = T.VARCHAR
DT = T.DATE

#: the calendar span covered by date_dim (and the fact sale dates fall
#: in the last five years of it, per the spec's 1998-2002 window)
DATE_LO = parse_date("1990-01-01")
DATE_HI = parse_date("2002-12-31")
SALES_LO = parse_date("1998-01-02")
SALES_HI = parse_date("2002-12-30")
#: spec surrogate key of 1998-01-01 (d_date_sk is a Julian day number)
SK_1998 = 2450815
_JD_OFFSET = SK_1998 - parse_date("1998-01-01")


def date_to_sk(days: np.ndarray | int):
    """DATE (days since epoch) -> d_date_sk (Julian day, spec-aligned)."""
    return days + _JD_OFFSET


#: named schema -> scale factor (mirrors the tpch connector)
SCHEMA_SF = {"tiny": 0.01, "sf1": 1.0, "sf10": 10.0, "sf100": 100.0}

# ---- schema (TPC-DS v2 spec, all 24 tables) --------------------------------

_ADDRESS = [
    ("street_number", V), ("street_name", V), ("street_type", V),
    ("suite_number", V), ("city", V), ("county", V), ("state", V),
    ("zip", V), ("country", V), ("gmt_offset", D52),
]

_SCHEMA_SPEC: dict[str, tuple[str, list[tuple[str, T.DataType]]]] = {
    "call_center": ("cc_", [
        ("call_center_sk", B), ("call_center_id", V),
        ("rec_start_date", DT), ("rec_end_date", DT),
        ("closed_date_sk", B), ("open_date_sk", B), ("name", V),
        ("class", V), ("employees", I), ("sq_ft", I), ("hours", V),
        ("manager", V), ("mkt_id", I), ("mkt_class", V), ("mkt_desc", V),
        ("market_manager", V), ("division", I), ("division_name", V),
        ("company", I), ("company_name", V), *_ADDRESS,
        ("tax_percentage", D52)]),
    "catalog_page": ("cp_", [
        ("catalog_page_sk", B), ("catalog_page_id", V),
        ("start_date_sk", B), ("end_date_sk", B), ("department", V),
        ("catalog_number", I), ("catalog_page_number", I),
        ("description", V), ("type", V)]),
    "catalog_returns": ("cr_", [
        ("returned_date_sk", B), ("returned_time_sk", B), ("item_sk", B),
        ("refunded_customer_sk", B), ("refunded_cdemo_sk", B),
        ("refunded_hdemo_sk", B), ("refunded_addr_sk", B),
        ("returning_customer_sk", B), ("returning_cdemo_sk", B),
        ("returning_hdemo_sk", B), ("returning_addr_sk", B),
        ("call_center_sk", B), ("catalog_page_sk", B), ("ship_mode_sk", B),
        ("warehouse_sk", B), ("reason_sk", B), ("order_number", B),
        ("return_quantity", I), ("return_amount", D72), ("return_tax", D72),
        ("return_amt_inc_tax", D72), ("fee", D72), ("return_ship_cost", D72),
        ("refunded_cash", D72), ("reversed_charge", D72),
        ("store_credit", D72), ("net_loss", D72)]),
    "catalog_sales": ("cs_", [
        ("sold_date_sk", B), ("sold_time_sk", B), ("ship_date_sk", B),
        ("bill_customer_sk", B), ("bill_cdemo_sk", B), ("bill_hdemo_sk", B),
        ("bill_addr_sk", B), ("ship_customer_sk", B), ("ship_cdemo_sk", B),
        ("ship_hdemo_sk", B), ("ship_addr_sk", B), ("call_center_sk", B),
        ("catalog_page_sk", B), ("ship_mode_sk", B), ("warehouse_sk", B),
        ("item_sk", B), ("promo_sk", B), ("order_number", B),
        ("quantity", I), ("wholesale_cost", D72), ("list_price", D72),
        ("sales_price", D72), ("ext_discount_amt", D72),
        ("ext_sales_price", D72), ("ext_wholesale_cost", D72),
        ("ext_list_price", D72), ("ext_tax", D72), ("coupon_amt", D72),
        ("ext_ship_cost", D72), ("net_paid", D72),
        ("net_paid_inc_tax", D72), ("net_paid_inc_ship", D72),
        ("net_paid_inc_ship_tax", D72), ("net_profit", D72)]),
    "customer": ("c_", [
        ("customer_sk", B), ("customer_id", V), ("current_cdemo_sk", B),
        ("current_hdemo_sk", B), ("current_addr_sk", B),
        ("first_shipto_date_sk", B), ("first_sales_date_sk", B),
        ("salutation", V), ("first_name", V), ("last_name", V),
        ("preferred_cust_flag", V), ("birth_day", I), ("birth_month", I),
        ("birth_year", I), ("birth_country", V), ("login", V),
        ("email_address", V), ("last_review_date_sk", B)]),
    "customer_address": ("ca_", [
        ("address_sk", B), ("address_id", V), *_ADDRESS,
        ("location_type", V)]),
    "customer_demographics": ("cd_", [
        ("demo_sk", B), ("gender", V), ("marital_status", V),
        ("education_status", V), ("purchase_estimate", I),
        ("credit_rating", V), ("dep_count", I),
        ("dep_employed_count", I), ("dep_college_count", I)]),
    "date_dim": ("d_", [
        ("date_sk", B), ("date_id", V), ("date", DT), ("month_seq", I),
        ("week_seq", I), ("quarter_seq", I), ("year", I), ("dow", I),
        ("moy", I), ("dom", I), ("qoy", I), ("fy_year", I),
        ("fy_quarter_seq", I), ("fy_week_seq", I), ("day_name", V),
        ("quarter_name", V), ("holiday", V), ("weekend", V),
        ("following_holiday", V), ("first_dom", I), ("last_dom", I),
        ("same_day_ly", I), ("same_day_lq", I), ("current_day", V),
        ("current_week", V), ("current_month", V), ("current_quarter", V),
        ("current_year", V)]),
    "household_demographics": ("hd_", [
        ("demo_sk", B), ("income_band_sk", B), ("buy_potential", V),
        ("dep_count", I), ("vehicle_count", I)]),
    "income_band": ("ib_", [
        ("income_band_sk", B), ("lower_bound", I), ("upper_bound", I)]),
    "inventory": ("inv_", [
        ("date_sk", B), ("item_sk", B), ("warehouse_sk", B),
        ("quantity_on_hand", I)]),
    "item": ("i_", [
        ("item_sk", B), ("item_id", V), ("rec_start_date", DT),
        ("rec_end_date", DT), ("item_desc", V), ("current_price", D72),
        ("wholesale_cost", D72), ("brand_id", I), ("brand", V),
        ("class_id", I), ("class", V), ("category_id", I), ("category", V),
        ("manufact_id", I), ("manufact", V), ("size", V),
        ("formulation", V), ("color", V), ("units", V), ("container", V),
        ("manager_id", I), ("product_name", V)]),
    "promotion": ("p_", [
        ("promo_sk", B), ("promo_id", V), ("start_date_sk", B),
        ("end_date_sk", B), ("item_sk", B), ("cost", D152),
        ("response_target", I), ("promo_name", V), ("channel_dmail", V),
        ("channel_email", V), ("channel_catalog", V), ("channel_tv", V),
        ("channel_radio", V), ("channel_press", V), ("channel_event", V),
        ("channel_demo", V), ("channel_details", V), ("purpose", V),
        ("discount_active", V)]),
    "reason": ("r_", [
        ("reason_sk", B), ("reason_id", V), ("reason_desc", V)]),
    "ship_mode": ("sm_", [
        ("ship_mode_sk", B), ("ship_mode_id", V), ("type", V),
        ("code", V), ("carrier", V), ("contract", V)]),
    "store": ("s_", [
        ("store_sk", B), ("store_id", V), ("rec_start_date", DT),
        ("rec_end_date", DT), ("closed_date_sk", B), ("store_name", V),
        ("number_employees", I), ("floor_space", I), ("hours", V),
        ("manager", V), ("market_id", I), ("geography_class", V),
        ("market_desc", V), ("market_manager", V), ("division_id", I),
        ("division_name", V), ("company_id", I), ("company_name", V),
        *_ADDRESS, ("tax_precentage", D52)]),  # spec's own spelling
    "store_returns": ("sr_", [
        ("returned_date_sk", B), ("return_time_sk", B), ("item_sk", B),
        ("customer_sk", B), ("cdemo_sk", B), ("hdemo_sk", B),
        ("addr_sk", B), ("store_sk", B), ("reason_sk", B),
        ("ticket_number", B), ("return_quantity", I), ("return_amt", D72),
        ("return_tax", D72), ("return_amt_inc_tax", D72), ("fee", D72),
        ("return_ship_cost", D72), ("refunded_cash", D72),
        ("reversed_charge", D72), ("store_credit", D72), ("net_loss", D72)]),
    "store_sales": ("ss_", [
        ("sold_date_sk", B), ("sold_time_sk", B), ("item_sk", B),
        ("customer_sk", B), ("cdemo_sk", B), ("hdemo_sk", B),
        ("addr_sk", B), ("store_sk", B), ("promo_sk", B),
        ("ticket_number", B), ("quantity", I), ("wholesale_cost", D72),
        ("list_price", D72), ("sales_price", D72),
        ("ext_discount_amt", D72), ("ext_sales_price", D72),
        ("ext_wholesale_cost", D72), ("ext_list_price", D72),
        ("ext_tax", D72), ("coupon_amt", D72), ("net_paid", D72),
        ("net_paid_inc_tax", D72), ("net_profit", D72)]),
    "time_dim": ("t_", [
        ("time_sk", B), ("time_id", V), ("time", I), ("hour", I),
        ("minute", I), ("second", I), ("am_pm", V), ("shift", V),
        ("sub_shift", V), ("meal_time", V)]),
    "warehouse": ("w_", [
        ("warehouse_sk", B), ("warehouse_id", V), ("warehouse_name", V),
        ("warehouse_sq_ft", I), *_ADDRESS]),
    "web_page": ("wp_", [
        ("web_page_sk", B), ("web_page_id", V), ("rec_start_date", DT),
        ("rec_end_date", DT), ("creation_date_sk", B),
        ("access_date_sk", B), ("autogen_flag", V), ("customer_sk", B),
        ("url", V), ("type", V), ("char_count", I), ("link_count", I),
        ("image_count", I), ("max_ad_count", I)]),
    "web_returns": ("wr_", [
        ("returned_date_sk", B), ("returned_time_sk", B), ("item_sk", B),
        ("refunded_customer_sk", B), ("refunded_cdemo_sk", B),
        ("refunded_hdemo_sk", B), ("refunded_addr_sk", B),
        ("returning_customer_sk", B), ("returning_cdemo_sk", B),
        ("returning_hdemo_sk", B), ("returning_addr_sk", B),
        ("web_page_sk", B), ("reason_sk", B), ("order_number", B),
        ("return_quantity", I), ("return_amt", D72), ("return_tax", D72),
        ("return_amt_inc_tax", D72), ("fee", D72),
        ("return_ship_cost", D72), ("refunded_cash", D72),
        ("reversed_charge", D72), ("account_credit", D72),
        ("net_loss", D72)]),
    "web_sales": ("ws_", [
        ("sold_date_sk", B), ("sold_time_sk", B), ("ship_date_sk", B),
        ("item_sk", B), ("bill_customer_sk", B), ("bill_cdemo_sk", B),
        ("bill_hdemo_sk", B), ("bill_addr_sk", B),
        ("ship_customer_sk", B), ("ship_cdemo_sk", B),
        ("ship_hdemo_sk", B), ("ship_addr_sk", B), ("web_page_sk", B),
        ("web_site_sk", B), ("ship_mode_sk", B), ("warehouse_sk", B),
        ("promo_sk", B), ("order_number", B), ("quantity", I),
        ("wholesale_cost", D72), ("list_price", D72), ("sales_price", D72),
        ("ext_discount_amt", D72), ("ext_sales_price", D72),
        ("ext_wholesale_cost", D72), ("ext_list_price", D72),
        ("ext_tax", D72), ("coupon_amt", D72), ("ext_ship_cost", D72),
        ("net_paid", D72), ("net_paid_inc_tax", D72),
        ("net_paid_inc_ship", D72), ("net_paid_inc_ship_tax", D72),
        ("net_profit", D72)]),
    "web_site": ("web_", [
        ("site_sk", B), ("site_id", V), ("rec_start_date", DT),
        ("rec_end_date", DT), ("name", V), ("open_date_sk", B),
        ("close_date_sk", B), ("class", V), ("manager", V), ("mkt_id", I),
        ("mkt_class", V), ("mkt_desc", V), ("market_manager", V),
        ("company_id", I), ("company_name", V), *_ADDRESS,
        ("tax_percentage", D52)]),
}

PREFIX = {t: p for t, (p, _) in _SCHEMA_SPEC.items()}

SCHEMAS: dict[str, TableSchema] = {
    t: TableSchema(t, [(p + c, ty) for c, ty in cols])
    for t, (p, cols) in _SCHEMA_SPEC.items()
}

# text pools (arbitrary deterministic vocabulary)
_CATEGORIES = (
    "Books", "Children", "Electronics", "Home", "Jewelry", "Men",
    "Music", "Shoes", "Sports", "Women",
)
_CLASSES = (
    "accessories", "athletic", "baseball", "classical", "computers",
    "dresses", "fiction", "kids", "pants", "romance", "scanners",
    "shirts",
)
_COLORS = (
    "aquamarine", "azure", "beige", "black", "blue", "chartreuse",
    "cream", "cyan", "forest", "gainsboro", "ghost", "green", "indian",
    "ivory", "khaki", "lavender", "magenta", "maroon", "navy", "olive",
    "orange", "orchid", "pale", "peach", "plum", "powder", "puff",
    "rose", "royal", "salmon", "seashell", "sienna", "sky", "slate",
    "smoke", "snow", "spring", "steel", "tan", "thistle", "tomato",
    "turquoise", "violet", "wheat", "white", "yellow",
)
_BUY_POTENTIAL = (
    "0-500", "501-1000", "1001-5000", "5001-10000", ">10000", "Unknown",
)
_EDUCATION = (
    "Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
    "Advanced Degree", "Unknown",
)
_CREDIT = ("Low Risk", "High Risk", "Good", "Unknown")
_CITIES = (
    "Fairview", "Midway", "Pleasant Hill", "Centerville", "Oak Grove",
    "Riverside", "Five Points", "Oakland", "Springdale", "Union",
    "Salem", "Georgetown", "Greenville", "Marion", "Glendale",
)
_COUNTIES = (
    "Williamson County", "Walker County", "Ziebach County",
    "Luce County", "Furnas County", "Richland County", "Gage County",
    "Daviess County", "Barrow County", "Franklin Parish",
)
_STATES = (
    "AL", "AR", "CA", "CO", "FL", "GA", "IA", "IL", "IN", "KS", "KY",
    "LA", "MI", "MN", "MO", "MS", "NC", "ND", "NE", "NY", "OH", "OK",
    "OR", "PA", "SC", "SD", "TN", "TX", "VA", "WA", "WI", "WV",
)
_STREETS = (
    "Main", "Oak", "Park", "Elm", "First", "Second", "Third", "Fourth",
    "Maple", "Pine", "Cedar", "Hill", "Lake", "Sunset", "Washington",
    "Jackson", "Lincoln", "Johnson", "Williams", "Davis",
)
_STREET_TYPES = (
    "Street", "Avenue", "Boulevard", "Circle", "Court", "Drive",
    "Lane", "Parkway", "Road", "Way",
)
_DESC_WORDS = (
    "able", "about", "account", "actual", "additional", "available",
    "basic", "careful", "certain", "clear", "common", "complete",
    "correct", "current", "different", "direct", "early", "easy",
    "entire", "exact", "final", "following", "free", "full", "general",
    "good", "great", "important", "large", "little", "local", "long",
    "major", "national", "natural", "necessary", "new", "normal",
    "old", "only", "open", "other", "particular", "political",
    "possible", "present", "private", "public", "real", "recent",
)


class TpcdsData:
    """All 24 TPC-DS tables at one scale factor, columns on demand."""

    def __init__(self, sf: float):
        self.sf = sf
        self._cache: dict[tuple[str, str], np.ndarray] = {}
        self._dates = np.arange(DATE_LO, DATE_HI + 1, dtype=np.int64)
        self._sale_days = np.arange(SALES_LO, SALES_HI + 1, dtype=np.int64)

    # ---- row counts ------------------------------------------------------

    def _n(self, base: int, minimum: int = 1) -> int:
        return max(minimum, round(base * self.sf))

    @property
    def n_item(self) -> int:
        return self._n(18_000, 200)

    @property
    def n_customer(self) -> int:
        return self._n(100_000, 1_000)

    @property
    def n_store(self) -> int:
        return self._n(12, 4)

    @property
    def n_warehouse(self) -> int:
        return self._n(5, 3)

    def row_count(self, table: str) -> int:
        fixed = {
            "date_dim": len(self._dates),
            "time_dim": 86_400,
            "income_band": 20,
            "ship_mode": 20,
            "household_demographics": 7_200,
        }
        if table in fixed:
            return fixed[table]
        if table == "inventory":
            weeks = len(self._sale_days[::7])
            return weeks * self.n_item * self.n_warehouse
        scaled = {
            "call_center": (6, 2),
            "catalog_page": (11_718, 200),
            "catalog_returns": (144_000, 1_500),
            "catalog_sales": (1_440_000, 15_000),
            "customer": (100_000, 1_000),
            "customer_address": (50_000, 500),
            "customer_demographics": (1_920_800, 19_208),
            "item": (18_000, 200),
            "promotion": (300, 10),
            "reason": (35, 5),
            "store": (12, 4),
            "store_returns": (288_000, 3_000),
            "store_sales": (2_880_000, 30_000),
            "warehouse": (5, 3),
            "web_page": (60, 10),
            "web_returns": (72_000, 750),
            "web_sales": (720_000, 7_500),
            "web_site": (30, 2),
        }
        base, minimum = scaled[table]
        return self._n(base, minimum)

    def _rng(self, table: str, stream: str) -> np.random.Generator:
        import zlib

        return np.random.default_rng([
            zlib.crc32(b"tpcds"), zlib.crc32(table.encode()),
            zlib.crc32(stream.encode()), int(self.sf * 1000),
        ])

    # ---- public API ------------------------------------------------------

    def column(self, table: str, name: str) -> np.ndarray:
        prefix = PREFIX[table]
        if name.startswith(prefix):
            name = name[len(prefix):]
        key = (table, name)
        if key not in self._cache:
            arr = self._generate(table, name)
            arr.setflags(write=False)
            self._cache[key] = arr
        return self._cache[key]

    def table(self, table: str) -> dict[str, np.ndarray]:
        return {c: self.column(table, c) for c in SCHEMAS[table].column_names}

    # ---- generic generators ----------------------------------------------

    def _generate(self, table: str, name: str) -> np.ndarray:
        special = getattr(self, f"_{table}__{name}", None)
        if special is not None:
            return special()
        n = self.row_count(table)
        rng = self._rng(table, name)
        prefix, cols = _SCHEMA_SPEC[table]
        typ = dict(cols).get(name)
        if typ is None:
            raise KeyError(f"no column {table}.{prefix}{name}")
        # structural defaults by column-name convention
        if name.endswith("_sk") and name == _sk_name(table):
            return np.arange(1, n + 1, dtype=np.int64)
        if name.endswith("_id") and isinstance(typ, T.VarcharType):
            # business-key strings only for VARCHAR ids; numeric *_id
            # columns (market_id, brand_id, manager_id...) fall through
            # to the integer generator
            return np.array(
                [f"{prefix.upper()}{i:012d}" for i in range(1, n + 1)],
                dtype=object,
            )
        if name.endswith("_sk"):
            dim = _FK_TARGET.get(name)
            if dim is not None:
                return self._fk_values(dim, rng, n)
            return rng.integers(1, n + 1, n).astype(np.int64)
        if isinstance(typ, T.DateType):
            return rng.choice(self._dates, n)
        if isinstance(typ, T.DecimalType):
            return rng.integers(0, 100_00, n).astype(np.int64)
        if isinstance(typ, T.IntegerKind):
            return rng.integers(0, 1000, n).astype(np.int64)
        # varchar: pooled words by convention
        pool = _TEXT_POOLS.get(name, _DESC_WORDS)
        return np.asarray(pool, dtype=object)[
            rng.integers(0, len(pool), n)
        ].astype(object)

    def _fk_values(self, dim: str, rng, n: int) -> np.ndarray:
        """Random foreign keys drawn from the dimension's ACTUAL key
        domain: date_dim keys are Julian-day numbers and time_dim keys
        are 0-based — a naive 1..row_count draw would never join."""
        if dim == "date_dim":
            return rng.choice(date_to_sk(self._dates), n)
        if dim == "time_dim":
            return rng.integers(0, 86_400, n).astype(np.int64)
        return rng.integers(1, self.row_count(dim) + 1, n).astype(np.int64)

    # ---- date_dim: a real calendar ---------------------------------------

    def _date_dim__date_sk(self):
        return date_to_sk(self._dates)

    def _date_dim__date(self):
        return self._dates.copy()

    def _date_dim__date_id(self):
        return np.array(
            [f"D{int(sk)}" for sk in date_to_sk(self._dates)], dtype=object
        )

    def _ymd(self):
        # vectorized civil calendar from days-since-epoch
        days = self._dates
        import datetime

        base = datetime.date(1970, 1, 1)
        ymd = np.array([
            (base + datetime.timedelta(days=int(d))).timetuple()[:3]
            for d in days
        ])
        return ymd[:, 0], ymd[:, 1], ymd[:, 2]

    def _date_dim__year(self):
        y, _, _ = self._ymd_cached()
        return y.astype(np.int64)

    def _ymd_cached(self):
        if not hasattr(self, "_ymd_memo"):
            self._ymd_memo = self._ymd()
        return self._ymd_memo

    def _date_dim__moy(self):
        _, m, _ = self._ymd_cached()
        return m.astype(np.int64)

    def _date_dim__dom(self):
        _, _, d = self._ymd_cached()
        return d.astype(np.int64)

    def _date_dim__qoy(self):
        _, m, _ = self._ymd_cached()
        return ((m - 1) // 3 + 1).astype(np.int64)

    def _date_dim__dow(self):
        # 1970-01-01 was a Thursday; spec dow 0 = Sunday
        return ((self._dates + 4) % 7).astype(np.int64)

    def _date_dim__week_seq(self):
        # weeks since the calendar start, Sunday-aligned (spec counts
        # from its own epoch; only equality/joins matter)
        return ((self._dates - DATE_LO + self._date_dim__dow()[0]) // 7 + 1).astype(np.int64)

    def _date_dim__month_seq(self):
        y, m, _ = self._ymd_cached()
        return ((y - 1990) * 12 + (m - 1)).astype(np.int64)

    def _date_dim__quarter_seq(self):
        y, m, _ = self._ymd_cached()
        return ((y - 1990) * 4 + (m - 1) // 3).astype(np.int64)

    def _date_dim__fy_year(self):
        return self._date_dim__year()

    def _date_dim__fy_quarter_seq(self):
        return self._date_dim__quarter_seq()

    def _date_dim__fy_week_seq(self):
        return self._date_dim__week_seq()

    def _date_dim__day_name(self):
        names = np.asarray([
            "Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
            "Friday", "Saturday",
        ], dtype=object)
        return names[self._date_dim__dow()]

    def _date_dim__quarter_name(self):
        y, m, _ = self._ymd_cached()
        return np.array(
            [f"{yy}Q{(mm - 1) // 3 + 1}" for yy, mm in zip(y, m)],
            dtype=object,
        )

    def _date_dim__holiday(self):
        _, m, d = self._ymd_cached()
        hol = ((m == 12) & (d == 25)) | ((m == 1) & (d == 1)) | (
            (m == 7) & (d == 4)
        )
        return np.where(hol, "Y", "N").astype(object)

    def _date_dim__weekend(self):
        dow = self._date_dim__dow()
        return np.where((dow == 0) | (dow == 6), "Y", "N").astype(object)

    def _date_dim__following_holiday(self):
        h = self._date_dim__holiday()
        return np.concatenate([["N"], h[:-1]]).astype(object)

    def _date_dim__first_dom(self):
        _, _, d = self._ymd_cached()
        return date_to_sk(self._dates - (d - 1))

    def _date_dim__last_dom(self):
        # approximation: sk of this month's 28th (only ordering is used)
        _, _, d = self._ymd_cached()
        return date_to_sk(self._dates - (d - 1) + 27)

    def _date_dim__same_day_ly(self):
        return date_to_sk(self._dates - 365)

    def _date_dim__same_day_lq(self):
        return date_to_sk(self._dates - 91)

    def _date_dim__current_day(self):
        return np.full(len(self._dates), "N", dtype=object)

    _date_dim__current_week = _date_dim__current_day
    _date_dim__current_month = _date_dim__current_day
    _date_dim__current_quarter = _date_dim__current_day
    _date_dim__current_year = _date_dim__current_day

    # ---- time_dim --------------------------------------------------------

    def _time_dim__time_sk(self):
        return np.arange(86_400, dtype=np.int64)

    def _time_dim__time(self):
        return np.arange(86_400, dtype=np.int64)

    def _time_dim__hour(self):
        return (np.arange(86_400) // 3600).astype(np.int64)

    def _time_dim__minute(self):
        return ((np.arange(86_400) % 3600) // 60).astype(np.int64)

    def _time_dim__second(self):
        return (np.arange(86_400) % 60).astype(np.int64)

    def _time_dim__am_pm(self):
        return np.where(np.arange(86_400) < 43_200, "AM", "PM").astype(object)

    def _time_dim__shift(self):
        h = self._time_dim__hour()
        return np.select(
            [h < 8, h < 16], ["third", "first"], "second"
        ).astype(object)

    def _time_dim__sub_shift(self):
        h = self._time_dim__hour()
        return np.select(
            [h < 6, h < 12, h < 18], ["night", "morning", "afternoon"],
            "evening",
        ).astype(object)

    def _time_dim__meal_time(self):
        h = self._time_dim__hour()
        return np.select(
            [(h >= 6) & (h < 9), (h >= 11) & (h < 14), (h >= 17) & (h < 21)],
            ["breakfast", "lunch", "dinner"], "",
        ).astype(object)

    # ---- income_band / demographics --------------------------------------

    def _income_band__lower_bound(self):
        return (np.arange(20, dtype=np.int64)) * 10_000

    def _income_band__upper_bound(self):
        return (np.arange(20, dtype=np.int64) + 1) * 10_000 - 1

    def _household_demographics__income_band_sk(self):
        return (np.arange(7_200, dtype=np.int64) % 20) + 1

    def _household_demographics__buy_potential(self):
        return np.asarray(_BUY_POTENTIAL, dtype=object)[
            np.arange(7_200) % len(_BUY_POTENTIAL)
        ]

    def _household_demographics__dep_count(self):
        return (np.arange(7_200, dtype=np.int64) // 6) % 10

    def _household_demographics__vehicle_count(self):
        return (np.arange(7_200, dtype=np.int64) // 60) % 5

    def _customer_demographics__gender(self):
        n = self.row_count("customer_demographics")
        return np.where(np.arange(n) % 2 == 0, "M", "F").astype(object)

    def _customer_demographics__marital_status(self):
        n = self.row_count("customer_demographics")
        pool = np.asarray(["M", "S", "D", "W", "U"], dtype=object)
        return pool[(np.arange(n) // 2) % 5]

    def _customer_demographics__education_status(self):
        n = self.row_count("customer_demographics")
        pool = np.asarray(_EDUCATION, dtype=object)
        return pool[(np.arange(n) // 10) % len(pool)]

    def _customer_demographics__credit_rating(self):
        n = self.row_count("customer_demographics")
        pool = np.asarray(_CREDIT, dtype=object)
        return pool[(np.arange(n) // 70) % len(pool)]

    def _customer_demographics__dep_count(self):
        n = self.row_count("customer_demographics")
        return ((np.arange(n, dtype=np.int64) // 280) % 7)

    def _customer_demographics__purchase_estimate(self):
        n = self.row_count("customer_demographics")
        return ((np.arange(n, dtype=np.int64) // 1960) % 20 + 1) * 500

    # ---- item ------------------------------------------------------------

    def _item__brand_id(self):
        rng = self._rng("item", "brand_id")
        return rng.integers(1_001_001, 1_010_016, self.n_item).astype(np.int64)

    def _item__brand(self):
        bid = self.column("item", "brand_id")
        return np.array(
            [f"brand#{int(b) % 1000}" for b in bid], dtype=object
        )

    def _item__category_id(self):
        rng = self._rng("item", "category_id")
        return rng.integers(1, len(_CATEGORIES) + 1, self.n_item).astype(np.int64)

    def _item__category(self):
        cid = self.column("item", "category_id")
        return np.asarray(_CATEGORIES, dtype=object)[cid - 1]

    def _item__class_id(self):
        rng = self._rng("item", "class_id")
        return rng.integers(1, len(_CLASSES) + 1, self.n_item).astype(np.int64)

    def _item__class(self):
        cid = self.column("item", "class_id")
        return np.asarray(_CLASSES, dtype=object)[cid - 1]

    def _item__manufact_id(self):
        rng = self._rng("item", "manufact_id")
        return rng.integers(1, 1_000, self.n_item).astype(np.int64)

    def _item__manufact(self):
        mid = self.column("item", "manufact_id")
        return np.array([f"manufact#{int(m)}" for m in mid], dtype=object)

    def _item__manager_id(self):
        rng = self._rng("item", "manager_id")
        return rng.integers(1, 101, self.n_item).astype(np.int64)

    def _item__current_price(self):
        rng = self._rng("item", "current_price")
        return rng.integers(100, 300_00, self.n_item).astype(np.int64)

    def _item__item_desc(self):
        rng = self._rng("item", "item_desc")
        words = np.asarray(_DESC_WORDS, dtype=object)
        k = 6
        picks = words[rng.integers(0, len(words), (self.n_item, k))]
        return np.array([" ".join(row) for row in picks], dtype=object)

    def _item__color(self):
        rng = self._rng("item", "color")
        return np.asarray(_COLORS, dtype=object)[
            rng.integers(0, len(_COLORS), self.n_item)
        ]

    # ---- sales facts: shared document structure --------------------------

    def _doc_lines(self, table: str, avg_lines: int):
        """(doc_id_per_row, line_count) — multi-line sales documents
        (ticket/order numbers repeating over consecutive rows)."""
        n = self.row_count(table)
        rng = self._rng(table, "doc")
        lens = rng.integers(1, 2 * avg_lines, n)  # enough docs to cover
        ends = np.cumsum(lens)
        doc_of_row = np.searchsorted(ends, np.arange(n), side="right")
        return (doc_of_row + 1).astype(np.int64)

    def _sold_date_sk(self, table: str):
        n = self.row_count(table)
        rng = self._rng(table, "sold_date")
        # one sale date per document so date filters align per order
        doc = self.column(table, _DOC_COL[table])
        n_docs = int(doc.max()) if n else 1
        doc_dates = rng.choice(self._sale_days, n_docs + 1)
        return date_to_sk(doc_dates[doc - 1])

    def _fact_prices(self, table: str, qty_col: str):
        """Internally consistent pricing block for one sales fact."""
        n = self.row_count(table)
        rng = self._rng(table, "pricing")
        qty = self.column(table, qty_col).astype(np.int64)
        wholesale = rng.integers(1_00, 100_00, n)
        markup = rng.integers(110, 220, n)
        list_p = wholesale * markup // 100
        discount = rng.integers(0, 60, n)
        sales_p = list_p * (100 - discount) // 100
        return qty, wholesale, list_p, sales_p

    # store_sales ----------------------------------------------------------

    def _store_sales__ticket_number(self):
        return self._doc_lines("store_sales", 10)

    def _store_sales__sold_date_sk(self):
        return self._sold_date_sk("store_sales")

    def _store_sales__quantity(self):
        rng = self._rng("store_sales", "quantity")
        return rng.integers(1, 101, self.row_count("store_sales")).astype(np.int64)

    def _store_sales__wholesale_cost(self):
        return self._fact_prices("store_sales", "quantity")[1]

    def _store_sales__list_price(self):
        return self._fact_prices("store_sales", "quantity")[2]

    def _store_sales__sales_price(self):
        return self._fact_prices("store_sales", "quantity")[3]

    def _store_sales__ext_discount_amt(self):
        q, _, lp, sp = self._fact_prices("store_sales", "quantity")
        return q * (lp - sp)

    def _store_sales__ext_sales_price(self):
        q, _, _, sp = self._fact_prices("store_sales", "quantity")
        return q * sp

    def _store_sales__ext_wholesale_cost(self):
        q, w, _, _ = self._fact_prices("store_sales", "quantity")
        return q * w

    def _store_sales__ext_list_price(self):
        q, _, lp, _ = self._fact_prices("store_sales", "quantity")
        return q * lp

    def _store_sales__ext_tax(self):
        return self._store_sales__ext_sales_price() * 8 // 100

    def _store_sales__coupon_amt(self):
        rng = self._rng("store_sales", "coupon")
        ext = self._store_sales__ext_sales_price()
        has = rng.random(len(ext)) < 0.1
        return np.where(has, ext // 10, 0)

    def _store_sales__net_paid(self):
        return (
            self._store_sales__ext_sales_price()
            - self._store_sales__coupon_amt()
        )

    def _store_sales__net_paid_inc_tax(self):
        return self._store_sales__net_paid() + self._store_sales__ext_tax()

    def _store_sales__net_profit(self):
        return (
            self._store_sales__net_paid()
            - self._store_sales__ext_wholesale_cost()
        )

    # catalog_sales / web_sales share the structure -------------------------

    def _catalog_sales__order_number(self):
        return self._doc_lines("catalog_sales", 6)

    def _catalog_sales__sold_date_sk(self):
        return self._sold_date_sk("catalog_sales")

    def _catalog_sales__ship_date_sk(self):
        rng = self._rng("catalog_sales", "ship_lag")
        lag = rng.integers(2, 90, self.row_count("catalog_sales"))
        return self.column("catalog_sales", "sold_date_sk") + lag

    def _catalog_sales__quantity(self):
        rng = self._rng("catalog_sales", "quantity")
        return rng.integers(1, 101, self.row_count("catalog_sales")).astype(np.int64)

    def _catalog_sales__wholesale_cost(self):
        return self._fact_prices("catalog_sales", "quantity")[1]

    def _catalog_sales__list_price(self):
        return self._fact_prices("catalog_sales", "quantity")[2]

    def _catalog_sales__sales_price(self):
        return self._fact_prices("catalog_sales", "quantity")[3]

    def _catalog_sales__ext_discount_amt(self):
        q, _, lp, sp = self._fact_prices("catalog_sales", "quantity")
        return q * (lp - sp)

    def _catalog_sales__ext_sales_price(self):
        q, _, _, sp = self._fact_prices("catalog_sales", "quantity")
        return q * sp

    def _catalog_sales__ext_wholesale_cost(self):
        q, w, _, _ = self._fact_prices("catalog_sales", "quantity")
        return q * w

    def _catalog_sales__ext_list_price(self):
        q, _, lp, _ = self._fact_prices("catalog_sales", "quantity")
        return q * lp

    def _catalog_sales__ext_tax(self):
        return self._catalog_sales__ext_sales_price() * 8 // 100

    def _catalog_sales__coupon_amt(self):
        rng = self._rng("catalog_sales", "coupon")
        ext = self._catalog_sales__ext_sales_price()
        has = rng.random(len(ext)) < 0.1
        return np.where(has, ext // 10, 0)

    def _catalog_sales__ext_ship_cost(self):
        return self._catalog_sales__ext_sales_price() // 20

    def _catalog_sales__net_paid(self):
        return (
            self._catalog_sales__ext_sales_price()
            - self._catalog_sales__coupon_amt()
        )

    def _catalog_sales__net_paid_inc_tax(self):
        return (
            self._catalog_sales__net_paid()
            + self._catalog_sales__ext_tax()
        )

    def _catalog_sales__net_paid_inc_ship(self):
        return (
            self._catalog_sales__net_paid()
            + self._catalog_sales__ext_ship_cost()
        )

    def _catalog_sales__net_paid_inc_ship_tax(self):
        return (
            self._catalog_sales__net_paid_inc_ship()
            + self._catalog_sales__ext_tax()
        )

    def _catalog_sales__net_profit(self):
        return (
            self._catalog_sales__net_paid()
            - self._catalog_sales__ext_wholesale_cost()
        )

    def _web_sales__order_number(self):
        return self._doc_lines("web_sales", 4)

    def _web_sales__sold_date_sk(self):
        return self._sold_date_sk("web_sales")

    def _web_sales__ship_date_sk(self):
        rng = self._rng("web_sales", "ship_lag")
        lag = rng.integers(1, 120, self.row_count("web_sales"))
        return self.column("web_sales", "sold_date_sk") + lag

    def _web_sales__quantity(self):
        rng = self._rng("web_sales", "quantity")
        return rng.integers(1, 101, self.row_count("web_sales")).astype(np.int64)

    def _web_sales__wholesale_cost(self):
        return self._fact_prices("web_sales", "quantity")[1]

    def _web_sales__list_price(self):
        return self._fact_prices("web_sales", "quantity")[2]

    def _web_sales__sales_price(self):
        return self._fact_prices("web_sales", "quantity")[3]

    def _web_sales__ext_discount_amt(self):
        q, _, lp, sp = self._fact_prices("web_sales", "quantity")
        return q * (lp - sp)

    def _web_sales__ext_sales_price(self):
        q, _, _, sp = self._fact_prices("web_sales", "quantity")
        return q * sp

    def _web_sales__ext_wholesale_cost(self):
        q, w, _, _ = self._fact_prices("web_sales", "quantity")
        return q * w

    def _web_sales__ext_list_price(self):
        q, _, lp, _ = self._fact_prices("web_sales", "quantity")
        return q * lp

    def _web_sales__ext_tax(self):
        return self._web_sales__ext_sales_price() * 8 // 100

    def _web_sales__coupon_amt(self):
        rng = self._rng("web_sales", "coupon")
        ext = self._web_sales__ext_sales_price()
        has = rng.random(len(ext)) < 0.1
        return np.where(has, ext // 10, 0)

    def _web_sales__ext_ship_cost(self):
        return self._web_sales__ext_sales_price() // 20

    def _web_sales__net_paid(self):
        return (
            self._web_sales__ext_sales_price()
            - self._web_sales__coupon_amt()
        )

    def _web_sales__net_paid_inc_tax(self):
        return self._web_sales__net_paid() + self._web_sales__ext_tax()

    def _web_sales__net_paid_inc_ship(self):
        return (
            self._web_sales__net_paid() + self._web_sales__ext_ship_cost()
        )

    def _web_sales__net_paid_inc_ship_tax(self):
        return (
            self._web_sales__net_paid_inc_ship()
            + self._web_sales__ext_tax()
        )

    def _web_sales__net_profit(self):
        return (
            self._web_sales__net_paid()
            - self._web_sales__ext_wholesale_cost()
        )

    # returns: subsets of the matching sales fact ---------------------------

    def _returns_pick(self, ret_table: str, sales_table: str):
        """Row indices into the sales fact that were returned."""
        n_ret = self.row_count(ret_table)
        n_sales = self.row_count(sales_table)
        rng = self._rng(ret_table, "pick")
        return rng.choice(n_sales, size=min(n_ret, n_sales), replace=False)

    def _ret_from_sales(self, ret_table, sales_table, col):
        pick = self._returns_pick(ret_table, sales_table)
        return self.column(sales_table, col)[pick]

    def _store_returns__ticket_number(self):
        return self._ret_from_sales(
            "store_returns", "store_sales", "ticket_number"
        )

    def _store_returns__item_sk(self):
        return self._ret_from_sales("store_returns", "store_sales", "item_sk")

    def _store_returns__customer_sk(self):
        return self._ret_from_sales(
            "store_returns", "store_sales", "customer_sk"
        )

    def _store_returns__store_sk(self):
        return self._ret_from_sales("store_returns", "store_sales", "store_sk")

    def _store_returns__returned_date_sk(self):
        rng = self._rng("store_returns", "lag")
        sold = self._ret_from_sales(
            "store_returns", "store_sales", "sold_date_sk"
        )
        return sold + rng.integers(1, 60, len(sold))

    def _store_returns__return_quantity(self):
        rng = self._rng("store_returns", "rq")
        q = self._ret_from_sales("store_returns", "store_sales", "quantity")
        return np.maximum(1, q * rng.integers(1, 101, len(q)) // 100)

    def _catalog_returns__order_number(self):
        return self._ret_from_sales(
            "catalog_returns", "catalog_sales", "order_number"
        )

    def _catalog_returns__item_sk(self):
        return self._ret_from_sales(
            "catalog_returns", "catalog_sales", "item_sk"
        )

    def _catalog_returns__returned_date_sk(self):
        rng = self._rng("catalog_returns", "lag")
        sold = self._ret_from_sales(
            "catalog_returns", "catalog_sales", "sold_date_sk"
        )
        return sold + rng.integers(1, 60, len(sold))

    def _catalog_returns__return_quantity(self):
        rng = self._rng("catalog_returns", "rq")
        q = self._ret_from_sales(
            "catalog_returns", "catalog_sales", "quantity"
        )
        return np.maximum(1, q * rng.integers(1, 101, len(q)) // 100)

    def _web_returns__order_number(self):
        return self._ret_from_sales(
            "web_returns", "web_sales", "order_number"
        )

    def _web_returns__item_sk(self):
        return self._ret_from_sales("web_returns", "web_sales", "item_sk")

    def _web_returns__returned_date_sk(self):
        rng = self._rng("web_returns", "lag")
        sold = self._ret_from_sales(
            "web_returns", "web_sales", "sold_date_sk"
        )
        return sold + rng.integers(1, 60, len(sold))

    def _web_returns__return_quantity(self):
        rng = self._rng("web_returns", "rq")
        q = self._ret_from_sales("web_returns", "web_sales", "quantity")
        return np.maximum(1, q * rng.integers(1, 101, len(q)) // 100)

    # inventory: weekly snapshots -------------------------------------------

    def _inventory__date_sk(self):
        weeks = date_to_sk(self._sale_days[::7])
        per_week = self.n_item * self.n_warehouse
        return np.repeat(weeks, per_week)

    def _inventory__item_sk(self):
        weeks = len(self._sale_days[::7])
        block = np.repeat(
            np.arange(1, self.n_item + 1, dtype=np.int64), self.n_warehouse
        )
        return np.tile(block, weeks)

    def _inventory__warehouse_sk(self):
        weeks = len(self._sale_days[::7])
        block = np.tile(
            np.arange(1, self.n_warehouse + 1, dtype=np.int64), self.n_item
        )
        return np.tile(block, weeks)

    def _inventory__quantity_on_hand(self):
        rng = self._rng("inventory", "qoh")
        return rng.integers(0, 1000, self.row_count("inventory")).astype(np.int64)

    # promotion -------------------------------------------------------------

    def _promotion__channel_dmail(self):
        rng = self._rng("promotion", "dmail")
        return np.where(
            rng.random(self.row_count("promotion")) < 0.5, "Y", "N"
        ).astype(object)

    _promotion__channel_email = _promotion__channel_dmail
    _promotion__channel_tv = _promotion__channel_dmail


def _sk_name(table: str) -> str:
    """The table's own surrogate-key column (bare name)."""
    return {
        "call_center": "call_center_sk",
        "catalog_page": "catalog_page_sk",
        "customer": "customer_sk",
        "customer_address": "address_sk",
        "customer_demographics": "demo_sk",
        "date_dim": "date_sk",
        "household_demographics": "demo_sk",
        "income_band": "income_band_sk",
        "item": "item_sk",
        "promotion": "promo_sk",
        "reason": "reason_sk",
        "ship_mode": "ship_mode_sk",
        "store": "store_sk",
        "time_dim": "time_sk",
        "warehouse": "warehouse_sk",
        "web_page": "web_page_sk",
        "web_site": "site_sk",
    }.get(table, "\x00none")


#: fk column (bare name) -> referenced table
_FK_TARGET = {
    "sold_date_sk": "date_dim", "ship_date_sk": "date_dim",
    "returned_date_sk": "date_dim", "sold_time_sk": "time_dim",
    "returned_time_sk": "time_dim", "return_time_sk": "time_dim",
    "item_sk": "item",
    "customer_sk": "customer", "bill_customer_sk": "customer",
    "ship_customer_sk": "customer", "refunded_customer_sk": "customer",
    "returning_customer_sk": "customer",
    "cdemo_sk": "customer_demographics",
    "bill_cdemo_sk": "customer_demographics",
    "ship_cdemo_sk": "customer_demographics",
    "refunded_cdemo_sk": "customer_demographics",
    "returning_cdemo_sk": "customer_demographics",
    "current_cdemo_sk": "customer_demographics",
    "hdemo_sk": "household_demographics",
    "bill_hdemo_sk": "household_demographics",
    "ship_hdemo_sk": "household_demographics",
    "refunded_hdemo_sk": "household_demographics",
    "returning_hdemo_sk": "household_demographics",
    "current_hdemo_sk": "household_demographics",
    "addr_sk": "customer_address", "bill_addr_sk": "customer_address",
    "ship_addr_sk": "customer_address",
    "refunded_addr_sk": "customer_address",
    "returning_addr_sk": "customer_address",
    "current_addr_sk": "customer_address",
    "store_sk": "store", "promo_sk": "promotion",
    "warehouse_sk": "warehouse", "call_center_sk": "call_center",
    "catalog_page_sk": "catalog_page", "ship_mode_sk": "ship_mode",
    "reason_sk": "reason", "web_page_sk": "web_page",
    "web_site_sk": "web_site", "site_sk": "web_site",
    "income_band_sk": "income_band",
    "first_shipto_date_sk": "date_dim",
    "first_sales_date_sk": "date_dim",
    "last_review_date_sk": "date_dim",
    "open_date_sk": "date_dim", "closed_date_sk": "date_dim",
    "close_date_sk": "date_dim", "start_date_sk": "date_dim",
    "end_date_sk": "date_dim", "creation_date_sk": "date_dim",
    "access_date_sk": "date_dim",
}

#: per-fact document-number column (bare name)
_DOC_COL = {
    "store_sales": "ticket_number",
    "catalog_sales": "order_number",
    "web_sales": "order_number",
}

#: text pools keyed by bare column name (fallback: _DESC_WORDS)
_TEXT_POOLS = {
    "city": _CITIES, "county": _COUNTIES, "state": _STATES,
    "street_name": _STREETS, "street_type": _STREET_TYPES,
    "country": ("United States",),
    "gender": ("M", "F"),
    "marital_status": ("M", "S", "D", "W", "U"),
    "education_status": _EDUCATION,
    "credit_rating": _CREDIT,
    "buy_potential": _BUY_POTENTIAL,
    "preferred_cust_flag": ("Y", "N"),
    "salutation": ("Mr.", "Mrs.", "Ms.", "Dr.", "Sir", "Miss"),
    "first_name": (
        "James", "John", "Robert", "Michael", "William", "David", "Mary",
        "Patricia", "Linda", "Barbara", "Elizabeth", "Jennifer",
    ),
    "last_name": (
        "Smith", "Johnson", "Williams", "Brown", "Jones", "Garcia",
        "Miller", "Davis", "Rodriguez", "Martinez", "Lopez", "Wilson",
    ),
    "birth_country": (
        "United States", "Canada", "Mexico", "Brazil", "Germany",
        "France", "Japan", "India", "China", "Australia",
    ),
    "color": _COLORS,
    "category": _CATEGORIES,
    "class": _CLASSES,
    "size": ("small", "medium", "large", "extra large", "petite", "N/A"),
    "units": ("Each", "Dozen", "Case", "Pallet", "Gross", "Box"),
    "container": ("Unknown", "Small Box", "Large Box", "Tub", "Crate"),
    "type": (
        "EXPRESS", "LIBRARY", "OVERNIGHT", "REGULAR", "TWO DAY",
        "NEXT DAY",
    ),
    "carrier": (
        "UPS", "FEDEX", "AIRBORNE", "USPS", "DHL", "TBS", "ZHOU",
        "LATVIAN",
    ),
    "am_pm": ("AM", "PM"),
    "hours": ("8AM-4PM", "8AM-8PM", "8AM-12AM"),
    "store_name": ("ought", "able", "pri", "ese", "anti", "cally"),
    "warehouse_name": (
        "Conventional childr", "Important issues liv", "Doors canno",
        "Bad cards must make.", "Operations can hide",
    ),
    "promo_name": ("ought", "able", "pri", "ese", "anti", "bar"),
    "purpose": ("Unknown", "ad hoc", "to build", "business"),
    "reason_desc": (
        "Package was damaged", "Stopped working", "Did not fit",
        "Not the product that was ordred", "Parts missing",
        "Found a better price in a store", "Gift exchange",
    ),
    "location_type": ("apartment", "condo", "single family"),
    "day_name": (
        "Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
        "Friday", "Saturday",
    ),
}
