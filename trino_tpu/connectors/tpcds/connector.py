"""TPC-DS connector: schemas tiny/sf1/sf10/sf100 of generated tables
(plugin/trino-tpcds/.../TpcdsConnectorFactory analog)."""

from __future__ import annotations

import numpy as np

from trino_tpu.connectors.base import (
    Connector,
    Split,
    TableSchema,
    TableStats,
    compute_column_stats,
)
from trino_tpu.connectors.tpcds.generator import (
    SCHEMA_SF,
    SCHEMAS,
    TpcdsData,
)

__all__ = ["TpcdsConnector"]


class TpcdsConnector(Connector):
    def __init__(self):
        self._data: dict[float, TpcdsData] = {}
        self._stats: dict[tuple[float, str], dict] = {}

    def data(self, schema: str) -> TpcdsData:
        sf = self._sf(schema)
        if sf not in self._data:
            self._data[sf] = TpcdsData(sf)
        return self._data[sf]

    @staticmethod
    def _sf(schema: str) -> float:
        if schema in SCHEMA_SF:
            return SCHEMA_SF[schema]
        if schema.startswith("sf"):
            try:
                return float(schema[2:])
            except ValueError:
                pass
        raise KeyError(f"unknown tpcds schema: {schema}")

    def list_schemas(self) -> list[str]:
        return list(SCHEMA_SF)

    def list_tables(self, schema: str) -> list[str]:
        return list(SCHEMAS)

    def table_schema(self, schema: str, table: str) -> TableSchema:
        return SCHEMAS[table]

    def row_count(self, schema: str, table: str) -> int:
        return self.data(schema).row_count(table)

    def column_stats(self, schema: str, table: str, column: str):
        """Per-column lazy stats (the reference ships precomputed tpcds
        stats files, plugin/trino-tpcds/.../statistics/): only columns
        a query touches are generated and measured."""
        sf = self._sf(schema)
        cols = self._stats.setdefault((sf, table), {})
        if column not in cols:
            cols[column] = compute_column_stats(
                self.data(schema).column(table, column)
            )
        return cols[column]

    def table_stats(self, schema: str, table: str) -> TableStats:
        cols = {
            c: self.column_stats(schema, table, c)
            for c in SCHEMAS[table].column_names
        }
        return TableStats(float(self.row_count(schema, table)), cols)

    def scan(
        self, schema: str, table: str, columns: list[str],
        split: Split | None = None,
    ) -> dict[str, np.ndarray]:
        data = self.data(schema)
        out = {}
        for c in columns:
            arr = data.column(table, c)
            if split is not None:
                arr = arr[split.start: split.start + split.count]
            out[c] = arr
        return out
