"""Parquet files connector: columnar file ingest to device pages.

The analog of the reference's Hive-style file connectors sitting on
lib/trino-parquet (ParquetReader,
lib/trino-parquet/.../reader/ParquetReader.java:85): a directory tree
``root/<schema>/<table>.parquet`` is exposed as catalog tables; scans
read only the projected columns (projection pushdown into the arrow
reader), nulls become validity masks, decimals become unscaled int64,
dates become int32 days — the engine's device page layout.

Row counts come from file metadata without touching data pages, the
footer-stats analog of the reference's stripe/rowgroup pruning.
"""

from __future__ import annotations

import os

import numpy as np

from trino_tpu import types as T
from trino_tpu.connectors.base import Connector, Split, TableSchema

__all__ = ["ParquetConnector", "write_parquet_table"]


def _arrow():
    import pyarrow
    import pyarrow.parquet as pq

    return pyarrow, pq


def _type_from_arrow(t) -> T.DataType:
    import pyarrow as pa

    if pa.types.is_boolean(t):
        return T.BOOLEAN
    if pa.types.is_int8(t):
        return T.TINYINT
    if pa.types.is_int16(t):
        return T.SMALLINT
    if pa.types.is_int32(t):
        return T.INTEGER
    if pa.types.is_int64(t):
        return T.BIGINT
    if pa.types.is_float32(t):
        return T.REAL
    if pa.types.is_float64(t):
        return T.DOUBLE
    if pa.types.is_decimal(t):
        if t.precision > 18:
            raise NotImplementedError(
                f"decimal precision {t.precision} > 18"
            )
        return T.DecimalType(t.precision, t.scale)
    if pa.types.is_date32(t):
        return T.DATE
    if pa.types.is_timestamp(t):
        if t.tz is not None:
            raise NotImplementedError(
                "timestamp with time zone is not supported yet"
            )
        return T.TIMESTAMP
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return T.VARCHAR
    raise NotImplementedError(f"parquet type {t}")


class ParquetConnector(Connector):
    def __init__(self, root: str):
        self.root = root
        self._schema_cache: dict[tuple[str, str], TableSchema] = {}

    def _path(self, schema: str, table: str) -> str:
        return os.path.join(self.root, schema, f"{table}.parquet")

    # ---- metadata --------------------------------------------------------

    def list_schemas(self) -> list[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(
            d for d in os.listdir(self.root)
            if os.path.isdir(os.path.join(self.root, d))
        )

    def list_tables(self, schema: str) -> list[str]:
        d = os.path.join(self.root, schema)
        if not os.path.isdir(d):
            return []
        return sorted(
            f[:-8] for f in os.listdir(d) if f.endswith(".parquet")
        )

    def table_schema(self, schema: str, table: str) -> TableSchema:
        key = (schema, table)
        if key not in self._schema_cache:
            _, pq = _arrow()
            meta = pq.read_schema(self._path(schema, table))
            cols = [
                (name, _type_from_arrow(meta.field(name).type))
                for name in meta.names
            ]
            self._schema_cache[key] = TableSchema(table, cols)
        return self._schema_cache[key]

    def row_count(self, schema: str, table: str) -> int:
        _, pq = _arrow()
        return pq.ParquetFile(self._path(schema, table)).metadata.num_rows

    # ---- scan ------------------------------------------------------------

    def scan(
        self, schema: str, table: str, columns: list[str],
        split: Split | None = None,
    ):
        _, pq = _arrow()
        ts = self.table_schema(schema, table)
        tbl = pq.read_table(self._path(schema, table), columns=list(columns))
        if split is not None:
            tbl = tbl.slice(split.start, split.count)
        out = {}
        for c in columns:
            arr = tbl.column(c).combine_chunks()
            out[c] = _to_host(arr, ts.column_type(c))
        return out


def _to_host(arr, t: T.DataType):
    """Arrow array -> (values, valid|None) in the engine's host layout."""
    valid = None
    if arr.null_count:
        valid = np.asarray(arr.is_valid())
    if isinstance(t, T.VarcharType):
        vals = np.asarray(
            ["" if v is None else v for v in arr.to_pylist()], dtype=object
        )
    elif isinstance(t, T.DecimalType):
        import pyarrow as pa

        unscaled = arr.cast(pa.decimal128(38, t.scale))
        vals = np.asarray(
            [0 if v is None else int(v.scaleb(t.scale)) for v in
             unscaled.to_pylist()],
            dtype=np.int64,
        )
    elif isinstance(t, T.DateType):
        import pyarrow as pa

        vals = np.asarray(arr.cast(pa.int32()).fill_null(0))
    elif isinstance(t, T.TimestampType):
        import pyarrow as pa

        vals = np.asarray(
            # safe=False: truncate sub-microsecond units (ns files)
            # like the reference rather than raising
            arr.cast(pa.timestamp("us"), safe=False)
            .cast(pa.int64()).fill_null(0)
        )
    else:
        vals = np.asarray(arr.fill_null(0) if arr.null_count else arr)
    return vals if valid is None else (vals, valid)


def write_parquet_table(
    root: str, schema: str, table: str, table_schema: TableSchema, columns: dict
):
    """Write host columns as one parquet file (the export half of the
    ingest path; the reference writes via ParquetWriter)."""
    pa, pq = _arrow()
    os.makedirs(os.path.join(root, schema), exist_ok=True)
    arrays = []
    names = []
    for c, t in table_schema.columns:
        vals = columns[c]
        valid = None
        if isinstance(vals, tuple):
            vals, valid = vals
        mask = None if valid is None else ~np.asarray(valid, dtype=bool)
        if isinstance(t, T.VarcharType):
            arr = pa.array(list(vals), type=pa.string(), mask=mask)
        elif isinstance(t, T.DecimalType):
            import decimal

            py = [
                decimal.Decimal(int(v)).scaleb(-t.scale)
                for v in np.asarray(vals)
            ]
            arr = pa.array(py, type=pa.decimal128(t.precision, t.scale), mask=mask)
        elif isinstance(t, T.DateType):
            arr = pa.array(
                np.asarray(vals, dtype=np.int32), type=pa.date32(), mask=mask
            )
        elif isinstance(t, T.TimestampType):
            arr = pa.array(
                np.asarray(vals, dtype=np.int64),
                type=pa.timestamp("us"), mask=mask,
            )
        else:
            arr = pa.array(np.asarray(vals), mask=mask)
        arrays.append(arr)
        names.append(c)
    pq.write_table(
        pa.Table.from_arrays(arrays, names=names),
        os.path.join(root, schema, f"{table}.parquet"),
    )
