"""Parquet files connector: out-of-core columnar storage scans.

The analog of the reference's Hive-style file connectors sitting on
lib/trino-parquet (ParquetReader,
lib/trino-parquet/.../reader/ParquetReader.java:85). Two layouts are
exposed as catalog tables:

- ``root/<schema>/<table>.parquet`` — a single file (legacy layout);
- ``root/<schema>/<table>/<key>=<value>/.../*.parquet`` — a Hive-style
  partitioned directory tree; the ``key=value`` path segments become
  synthesized partition columns appended to the file schema.

A per-table *manifest* (file list + per-row-group footer stats, global
row offsets) is built once from metadata only — no data page is
touched. The manifest defines a global row order (files sorted by
relative path), so a ``Split`` stays a plain ``(start, count)`` row
range and the whole engine's split plumbing (serde, fleet binding,
streamed chunking) works unchanged; the connector maps any row range
back to the covering row groups at read time.

Pushdown happens at three levels, mirroring the reference:
- projection: only requested columns are decoded (ParquetReader column
  projection);
- partition pruning: ``key=value`` directories disjoint with a column
  domain are skipped without opening any file (HivePartitionManager);
- row-group pruning: footer min/max statistics disjoint with a domain
  skip the row group (TupleDomain → ParquetPredicate stripe pruning).

Nulls become validity masks, short decimals unscaled int64, decimals
with precision > 18 the engine's two-limb ``[n, 2]`` int64 layout,
dates int32 days, timestamps int64 micros — the device page layout.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

import numpy as np

from trino_tpu import telemetry
from trino_tpu import types as T
from trino_tpu.connectors.base import (
    ColumnStats, Connector, Split, TableSchema, TableStats, WriteSink,
)

__all__ = ["ParquetConnector", "write_parquet_table"]


def _arrow():
    import pyarrow
    import pyarrow.parquet as pq

    return pyarrow, pq


def _type_from_arrow(t) -> T.DataType:
    import pyarrow as pa

    if pa.types.is_boolean(t):
        return T.BOOLEAN
    if pa.types.is_int8(t):
        return T.TINYINT
    if pa.types.is_int16(t):
        return T.SMALLINT
    if pa.types.is_int32(t):
        return T.INTEGER
    if pa.types.is_int64(t):
        return T.BIGINT
    if pa.types.is_float32(t):
        return T.REAL
    if pa.types.is_float64(t):
        return T.DOUBLE
    if pa.types.is_decimal(t):
        # precision > 18 maps onto the engine's two-limb decimal(38)
        # host layout; DecimalType itself validates precision <= 38
        return T.DecimalType(t.precision, t.scale)
    if pa.types.is_date32(t):
        return T.DATE
    if pa.types.is_timestamp(t):
        if t.tz is not None:
            raise NotImplementedError(
                "timestamp with time zone is not supported yet"
            )
        return T.TIMESTAMP
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return T.VARCHAR
    raise NotImplementedError(f"parquet type {t}")


@dataclass
class _RowGroup:
    """One row group of one file, addressed in GLOBAL row order."""

    index: int          #: row-group index within its file
    start: int          #: global row offset
    count: int
    size_bytes: int
    #: column -> (lo, hi) in storage domain, footer min/max only
    stats: dict = field(default_factory=dict)


@dataclass
class _FileEntry:
    path: str
    start: int          #: global row offset of the file's first row
    count: int
    #: partition column -> typed value parsed from key=value segments
    partition: dict = field(default_factory=dict)
    row_groups: list = field(default_factory=list)


@dataclass
class _Manifest:
    files: list
    row_count: int
    #: [(name, DataType)] for synthesized partition columns
    partition_cols: list
    total_bytes: int
    rowgroups_total: int


class ParquetConnector(Connector):
    #: scan()/splits() accept ColumnDomains and prune partitions +
    #: rowgroups by footer statistics (ParquetReader's predicate
    #: pushdown, lib/trino-parquet/.../reader/ParquetReader.java:85)
    supports_domains = True

    #: scans can be iterated split-by-split without materializing the
    #: table — the executor may route through exec/stream_scan.py
    streamable = True

    def __init__(self, root: str, split_target_bytes: int = 64 << 20):
        self.root = root
        #: coalescing ceiling for splits() (Hive max-split-size analog)
        self.split_target_bytes = split_target_bytes
        self._schema_cache: dict[tuple[str, str], TableSchema] = {}
        self._manifest_cache: dict[tuple[str, str], _Manifest] = {}
        #: metrics of the LAST pruned scan / split enumeration (tests +
        #: EXPLAIN ANALYZE — the connector Metrics SPI analog,
        #: SPI/metrics/Metrics.java)
        self.scan_metrics: dict = {}

    def cache_fingerprint(self):
        """``(ident, content)`` for the cross-query caches (cache.py):
        the absolute root path names the data — two connector instances
        over the same files share cache entries — and the content
        digest (relative path + size + mtime_ns of every parquet file)
        busts them when anything on disk is rewritten out-of-band."""
        import hashlib

        root = os.path.abspath(self.root)
        h = hashlib.blake2b(digest_size=12)
        try:
            for dirpath, dirnames, filenames in os.walk(root):
                # uncommitted staging epochs are invisible to readers
                # and must not bust reader caches while a CTAS runs
                dirnames[:] = [
                    d for d in dirnames if not d.startswith("_tmp")
                ]
                dirnames.sort()
                for fn in sorted(filenames):
                    if not fn.endswith(".parquet"):
                        continue
                    p = os.path.join(dirpath, fn)
                    st = os.stat(p)
                    rel = os.path.relpath(p, root)
                    h.update(
                        f"{rel}:{st.st_size}:{st.st_mtime_ns};".encode()
                    )
        except OSError:
            return None  # unreadable root: per-instance isolation
        return f"parquet:{root}", h.hexdigest()

    def _file_path(self, schema: str, table: str) -> str:
        return os.path.join(self.root, schema, f"{table}.parquet")

    def _dir_path(self, schema: str, table: str) -> str:
        return os.path.join(self.root, schema, table)

    # ---- metadata --------------------------------------------------------

    def list_schemas(self) -> list[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(
            d for d in os.listdir(self.root)
            if os.path.isdir(os.path.join(self.root, d))
        )

    def list_tables(self, schema: str) -> list[str]:
        d = os.path.join(self.root, schema)
        if not os.path.isdir(d):
            return []
        out = set()
        for f in os.listdir(d):
            if f.endswith(".parquet"):
                out.add(f[:-8])
            elif os.path.isdir(os.path.join(d, f)) and not f.startswith(
                "_tmp"
            ):
                # _tmp_{token} staging epochs are not tables
                out.add(f)
        return sorted(out)

    def invalidate(self, schema: str | None = None, table: str | None = None):
        """Drop cached manifests/schemas (after an external write)."""
        if schema is None:
            self._schema_cache.clear()
            self._manifest_cache.clear()
        else:
            self._schema_cache.pop((schema, table), None)
            self._manifest_cache.pop((schema, table), None)

    def _manifest(self, schema: str, table: str) -> _Manifest:
        key = (schema, table)
        m = self._manifest_cache.get(key)
        if m is None:
            m = self._build_manifest(schema, table)
            self._manifest_cache[key] = m
        return m

    def _data_files(self, schema: str, table: str) -> list[str]:
        """Data file paths in global row order (sorted relative path)."""
        single = self._file_path(schema, table)
        if os.path.isfile(single):
            return [single]
        d = self._dir_path(schema, table)
        if not os.path.isdir(d):
            raise FileNotFoundError(single)
        found = []
        for base, _dirs, names in os.walk(d):
            for n in names:
                if n.endswith(".parquet"):
                    found.append(os.path.join(base, n))
        if not found:
            raise FileNotFoundError(f"no parquet files under {d}")
        return sorted(found)

    def _build_manifest(self, schema: str, table: str) -> _Manifest:
        _, pq = _arrow()
        paths = self._data_files(schema, table)
        d = self._dir_path(schema, table)
        # partition keys from key=value path segments; value type is
        # BIGINT only when EVERY file's value parses as int
        raw_parts: list[dict[str, str]] = []
        for p in paths:
            parts = {}
            rel = os.path.relpath(os.path.dirname(p), d)
            if rel != "." and not os.path.isfile(
                self._file_path(schema, table)
            ):
                for seg in rel.split(os.sep):
                    if "=" in seg:
                        k, _, v = seg.partition("=")
                        parts[k] = v
            raw_parts.append(parts)
        pkeys = list(dict.fromkeys(k for rp in raw_parts for k in rp))
        ptypes = {}
        for k in pkeys:
            vals = [rp.get(k) for rp in raw_parts]
            if any(v is None for v in vals):
                raise ValueError(
                    f"partition key {k!r} missing from some files of "
                    f"{schema}.{table}"
                )
            try:
                [int(v) for v in vals]
                ptypes[k] = T.BIGINT
            except ValueError:
                ptypes[k] = T.VARCHAR
        base_schema = self._file_table_schema(schema, table, paths[0])
        files = []
        start = 0
        total_bytes = 0
        rg_total = 0
        for p, rp in zip(paths, raw_parts):
            md = pq.ParquetFile(p).metadata
            part = {
                k: (int(rp[k]) if ptypes[k] is T.BIGINT else rp[k])
                for k in pkeys
            }
            fe = _FileEntry(p, start, md.num_rows, part)
            name_to_idx = {
                md.row_group(0).column(j).path_in_schema: j
                for j in range(md.row_group(0).num_columns)
            } if md.num_row_groups else {}
            for i in range(md.num_row_groups):
                rg = md.row_group(i)
                stats = {}
                for cname, j in name_to_idx.items():
                    st = rg.column(j).statistics
                    if st is None or not st.has_min_max:
                        continue
                    try:
                        t = base_schema.column_type(cname)
                    except KeyError:
                        continue
                    stats[cname] = (
                        _stat_to_storage(st.min, t),
                        _stat_to_storage(st.max, t),
                    )
                # partition values are exact single-value bounds
                for k, v in part.items():
                    stats[k] = (v, v)
                nbytes = rg.total_byte_size
                fe.row_groups.append(
                    _RowGroup(i, start, rg.num_rows, nbytes, stats)
                )
                start += rg.num_rows
                total_bytes += nbytes
                rg_total += 1
            files.append(fe)
        return _Manifest(
            files, start, [(k, ptypes[k]) for k in pkeys],
            total_bytes, rg_total,
        )

    def _file_table_schema(
        self, schema: str, table: str, path: str
    ) -> TableSchema:
        _, pq = _arrow()
        meta = pq.read_schema(path)
        return TableSchema(table, [
            (name, _type_from_arrow(meta.field(name).type))
            for name in meta.names
        ])

    def table_schema(self, schema: str, table: str) -> TableSchema:
        key = (schema, table)
        if key not in self._schema_cache:
            m = self._manifest(schema, table)
            ts = self._file_table_schema(schema, table, m.files[0].path)
            cols = list(ts.columns) + [
                (k, t) for k, t in m.partition_cols
                if k not in ts.column_names
            ]
            self._schema_cache[key] = TableSchema(table, cols)
        return self._schema_cache[key]

    def row_count(self, schema: str, table: str) -> int:
        return self._manifest(schema, table).row_count

    def table_stats(self, schema: str, table: str) -> TableStats:
        """Row count + exact per-column min/max merged from footers (no
        data pages touched) — feeds join ordering and df_range_keep."""
        m = self._manifest(schema, table)
        merged: dict[str, list] = {}
        counted: dict[str, int] = {}
        for fe in m.files:
            for rg in fe.row_groups:
                for c, (lo, hi) in rg.stats.items():
                    if lo is None or hi is None or isinstance(lo, str):
                        continue
                    cur = merged.get(c)
                    if cur is None:
                        merged[c] = [lo, hi]
                    else:
                        cur[0] = min(cur[0], lo)
                        cur[1] = max(cur[1], hi)
                    counted[c] = counted.get(c, 0) + rg.count
        cols = {
            c: ColumnStats(lo=v[0], hi=v[1])
            for c, v in merged.items()
            # only exact bounds: every row group must have reported
            if counted.get(c, 0) == m.row_count
        }
        return TableStats(float(m.row_count), cols)

    # ---- distributed write (TableWriter subsystem) -----------------------
    #
    # Writers stage row-group-sized part files under
    # ``root/schema/_tmp_{token}/table/[key=value/...]`` (a SIBLING of
    # the table dir, so readers never walk uncommitted data); commit
    # verifies each fragment's CRC, atomically renames winners into the
    # Hive-style table tree, records ``_manifest.json`` (the idempotent
    # commit marker), removes the whole staging epoch (loser-attempt
    # orphans included) and invalidates cached metadata so splits()/
    # table_stats see the new data immediately.

    def _staging_dir(self, schema: str, table: str, token: str) -> str:
        return os.path.join(
            self.root, schema, f"_tmp_{token or 'local'}", table
        )

    def begin_insert(self, schema: str, table: str) -> dict:
        ts = self.table_schema(schema, table)  # raises if missing
        m = self._manifest(schema, table)
        return {
            "schema": schema, "table": table, "mode": "insert",
            "columns": [[c, str(t)] for c, t in ts.columns],
            "partition_by": [k for k, _t in m.partition_cols],
            "row_group_size": None,
        }

    def begin_create(
        self, schema: str, table: str, table_schema: TableSchema,
        partition_by=None, properties=None,
    ) -> dict:
        partition_by = list(partition_by or [])
        for k in partition_by:
            t = table_schema.column_type(k)  # KeyError if unknown
            if not (t.is_integer or isinstance(t, T.VarcharType)):
                raise ValueError(
                    f"partition column {k!r} must be integer or varchar"
                )
        rgs = (properties or {}).get("row_group_size")
        return {
            "schema": schema, "table": table, "mode": "create",
            "columns": [[c, str(t)] for c, t in table_schema.columns],
            "partition_by": partition_by,
            "row_group_size": None if rgs is None else int(rgs),
        }

    def write_sink(self, handle: dict, ctx: dict | None = None):
        return _ParquetSink(self.root, handle, ctx)

    def finish_write(
        self, handle: dict, fragments: list[str], token: str = "",
    ) -> int:
        import json
        import shutil
        import zlib

        schema, table = handle["schema"], handle["table"]
        tdir = self._dir_path(schema, table)
        staging = self._staging_dir(schema, table, token)
        manifest_path = os.path.join(tdir, "_manifest.json")
        prior = None
        if os.path.isfile(manifest_path):
            with open(manifest_path) as f:
                prior = json.load(f)
            if token and prior.get("token") == token:
                # replayed commit (coordinator crashed after commit,
                # before the client saw the result): already applied
                shutil.rmtree(
                    os.path.dirname(staging), ignore_errors=True
                )
                return int(prior.get("rows", 0))
        single = self._file_path(schema, table)
        if handle["mode"] == "insert" and os.path.isfile(single):
            # legacy single-file table gains part files: fold the
            # original file into the directory layout first
            os.makedirs(tdir, exist_ok=True)
            os.replace(
                single, os.path.join(tdir, "part-00000-legacy.parquet")
            )
        frags = [json.loads(s) for s in fragments]
        total_rows = 0
        entries = list(prior["files"]) if prior else []
        # a fragment path already in the manifest belongs to COMMITTED
        # data — renaming over it would silently destroy rows (part
        # names carry the epoch precisely so this cannot happen; treat
        # a collision as corruption, not as an update)
        dup = {e["path"] for e in entries} & {fr["path"] for fr in frags}
        if dup:
            raise IOError(
                f"write fragments collide with committed part files "
                f"{sorted(dup)}; refusing to overwrite"
            )
        touched_dirs = set()
        for fr in frags:
            staged = os.path.join(staging, fr["path"])
            dest = os.path.join(tdir, fr["path"])
            if not os.path.isfile(staged):
                if os.path.isfile(dest) and os.path.getsize(dest) == int(
                    fr["bytes"]
                ):
                    # crashed between this rename and the manifest
                    # write on a previous commit attempt
                    total_rows += int(fr["rows"])
                    entries.append(_manifest_entry(fr))
                    continue
                raise FileNotFoundError(
                    f"staged write fragment missing: {staged}"
                )
            with open(staged, "rb") as f:
                crc = zlib.crc32(f.read()) & 0xFFFFFFFF
            if crc != int(fr["crc"]):
                raise IOError(
                    f"write fragment CRC mismatch for {fr['path']}: "
                    f"staged file is corrupt, refusing to commit"
                )
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            os.replace(staged, dest)
            touched_dirs.add(os.path.dirname(dest))
            total_rows += int(fr["rows"])
            entries.append(_manifest_entry(fr))
        for d in sorted(touched_dirs):
            _fsync_dir(d)
        os.makedirs(tdir, exist_ok=True)
        if handle["mode"] == "create" and not frags:
            # empty CTAS: the table must still be readable, so write
            # one zero-row part file carrying the schema
            from trino_tpu.connectors.base import (
                handle_table_schema, rows_to_columns,
            )

            ts = handle_table_schema(handle)
            fs = TableSchema(table, [
                (c, t) for c, t in ts.columns
                if c not in (handle.get("partition_by") or [])
            ])
            empty = rows_to_columns(fs, fs.column_names, [])
            _write_file(
                os.path.join(tdir, "part-empty-0000.parquet"),
                fs, empty, fsync=True,
            )
        tmp = manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {
                    "token": token,
                    "rows": total_rows,
                    "files": entries,
                },
                f,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, manifest_path)
        _fsync_dir(tdir)
        # the epoch's staging root also holds losing speculated
        # attempts' part files — drop them all (zero orphans)
        shutil.rmtree(os.path.dirname(staging), ignore_errors=True)
        self.invalidate(schema, table)
        return total_rows

    def abort_write(self, handle: dict, token: str = ""):
        import shutil

        staging = self._staging_dir(handle["schema"], handle["table"], token)
        shutil.rmtree(os.path.dirname(staging), ignore_errors=True)

    # ---- splits ----------------------------------------------------------

    def splits(
        self, schema: str, table: str, target_splits: int,
        domains: dict | None = None,
    ) -> list[Split]:
        """One Split per surviving row group, coalesced to a byte
        target (the Hive split model: HiveSplitSource + max-split-size
        coalescing). ``domains`` prunes partitions and row groups from
        footer stats before any split exists; never coalesces across a
        pruned or non-adjacent row group, so a split's row range reads
        back exactly its surviving row groups."""
        m = self._manifest(schema, table)
        domains = domains or {}
        pruned_partitions: set[tuple] = set()
        all_partitions: set[tuple] = set()
        survivors: list[_RowGroup] = []
        rg_pruned = 0
        live_bytes = 0
        for fe in m.files:
            pkey = tuple(sorted(fe.partition.items()))
            if fe.partition:
                all_partitions.add(pkey)
            if fe.partition and any(
                dom is not None and dom.disjoint(
                    fe.partition[k], fe.partition[k]
                )
                for k, dom in domains.items() if k in fe.partition
            ):
                pruned_partitions.add(pkey)
                continue
            for rg in fe.row_groups:
                if any(
                    dom is not None and c in rg.stats
                    and dom.disjoint(*rg.stats[c])
                    for c, dom in domains.items()
                ):
                    rg_pruned += 1
                    continue
                survivors.append(rg)
                live_bytes += rg.size_bytes
        target_splits = max(1, target_splits)
        target_bytes = min(
            self.split_target_bytes,
            max(1, -(-live_bytes // target_splits)),
        )
        out: list[Split] = []
        cur: list[_RowGroup] = []
        cur_bytes = 0

        def _flush():
            nonlocal cur, cur_bytes
            if not cur:
                return
            stats: dict[str, list] = {}
            # merge bounds; a column must appear in EVERY member to
            # stay (a missing footer stat means unknown, not empty)
            common = set(cur[0].stats)
            for rg in cur[1:]:
                common &= set(rg.stats)
            for c in common:
                los = [rg.stats[c][0] for rg in cur]
                his = [rg.stats[c][1] for rg in cur]
                if any(v is None for v in los + his):
                    continue
                try:
                    stats[c] = [min(los), max(his)]
                except TypeError:
                    continue
            out.append(Split(
                table, cur[0].start, sum(rg.count for rg in cur),
                size_bytes=cur_bytes,
                stats=tuple(
                    (c, lo, hi) for c, (lo, hi) in sorted(stats.items())
                ),
            ))
            cur, cur_bytes = [], 0

        for rg in survivors:
            adjacent = bool(cur) and cur[-1].start + cur[-1].count == rg.start
            if cur and (
                not adjacent or cur_bytes + rg.size_bytes > target_bytes
            ):
                _flush()
            cur.append(rg)
            cur_bytes += rg.size_bytes
        _flush()
        self.scan_metrics = {
            "rowgroups_total": m.rowgroups_total,
            "rowgroups_read": len(survivors),
            "rowgroups_pruned": rg_pruned,
            "partitions_total": len(all_partitions),
            "partitions_pruned": len(pruned_partitions),
            "splits": len(out),
        }
        telemetry.SCAN_ROWGROUPS_TOTAL.inc(m.rowgroups_total, table=table)
        telemetry.SCAN_ROWGROUPS_PRUNED.inc(rg_pruned, table=table)
        telemetry.SCAN_PARTITIONS_PRUNED.inc(
            len(pruned_partitions), table=table
        )
        return out or [Split(table, 0, 0)]

    # ---- scan ------------------------------------------------------------

    def scan(
        self, schema: str, table: str, columns: list[str],
        split: Split | None = None, domains=None,
    ):
        """Produce host arrays for the requested columns.

        ``split`` may be ANY global row range — not just one produced
        by splits(): the streamed-chunk reader slices uniform chunks.
        Only row groups overlapping the range are decoded; ``domains``
        additionally skips stats-disjoint row groups (pruning-safe: the
        engine re-applies the full filter)."""
        m = self._manifest(schema, table)
        ts = self.table_schema(schema, table)
        lo = 0 if split is None else split.start
        hi = m.row_count if split is None else min(
            m.row_count, split.start + split.count
        )
        domains = domains or {}
        pcols = {k for k, _ in m.partition_cols}
        file_cols = [c for c in columns if c not in pcols]
        pieces: list[tuple[int, dict]] = []  # (n_rows, col -> host)
        rg_total = 0
        rg_read = 0
        parts_pruned: set[tuple] = set()
        bytes_read = 0
        for fe in m.files:
            if fe.start >= hi or fe.start + fe.count <= lo:
                continue
            rg_total += len(fe.row_groups)
            if fe.partition and any(
                dom is not None and dom.disjoint(
                    fe.partition[k], fe.partition[k]
                )
                for k, dom in domains.items() if k in fe.partition
            ):
                parts_pruned.add(tuple(sorted(fe.partition.items())))
                continue
            keep = []
            for rg in fe.row_groups:
                if rg.start >= hi or rg.start + rg.count <= lo:
                    continue
                if any(
                    dom is not None and c in rg.stats
                    and dom.disjoint(*rg.stats[c])
                    for c, dom in domains.items()
                ):
                    continue
                keep.append(rg)
            if not keep:
                continue
            rg_read += len(keep)
            bytes_read += sum(rg.size_bytes for rg in keep)
            n, cols = self._read_file_rowgroups(fe, keep, file_cols, ts, lo, hi)
            if n == 0:
                continue
            for k, t in m.partition_cols:
                if k in columns and k not in cols:
                    cols[k] = _const_column(fe.partition[k], t, n)
            pieces.append((n, cols))
        telemetry.SCAN_BYTES_READ.inc(bytes_read, table=table)
        if split is None and domains:
            # whole-table pruned scan: report connector metrics the way
            # the legacy single-file path always did
            self.scan_metrics = {
                "rowgroups_total": rg_total,
                "rowgroups_read": rg_read,
                "rowgroups_pruned": rg_total - rg_read
                - sum(
                    len(fe.row_groups) for fe in m.files
                    if tuple(sorted(fe.partition.items())) in parts_pruned
                ),
                "partitions_pruned": len(parts_pruned),
            }
            telemetry.SCAN_ROWGROUPS_TOTAL.inc(rg_total, table=table)
            telemetry.SCAN_ROWGROUPS_PRUNED.inc(
                self.scan_metrics["rowgroups_pruned"], table=table
            )
            telemetry.SCAN_PARTITIONS_PRUNED.inc(
                len(parts_pruned), table=table
            )
        return _concat_pieces(pieces, columns, ts)

    def _read_file_rowgroups(
        self, fe: _FileEntry, keep: list, file_cols: list,
        ts: TableSchema, lo: int, hi: int,
    ):
        """Decode the kept row groups of one file, sliced to the global
        [lo, hi) range; returns (n_rows, col -> host arrays)."""
        _, pq = _arrow()
        # kept row groups are contiguous-or-not; read them as one arrow
        # table (global offsets of each are known, so edge-slice per
        # contiguous run)
        runs: list[list] = []
        for rg in keep:
            if runs and runs[-1][-1].index + 1 == rg.index and (
                runs[-1][-1].start + runs[-1][-1].count == rg.start
            ):
                runs[-1].append(rg)
            else:
                runs.append([rg])
        pf = pq.ParquetFile(fe.path)
        n_total = 0
        per_col: dict[str, list] = {c: [] for c in file_cols}
        for run in runs:
            run_start = run[0].start
            run_count = sum(rg.count for rg in run)
            off = max(0, lo - run_start)
            take = min(run_start + run_count, hi) - max(run_start, lo)
            if take <= 0:
                continue
            if file_cols:
                tbl = pf.read_row_groups(
                    [rg.index for rg in run], columns=list(file_cols)
                )
                if off or take != run_count:
                    tbl = tbl.slice(off, take)
                for c in file_cols:
                    per_col[c].append(tbl.column(c))
            n_total += take
        out = {}
        for c in file_cols:
            arrs = per_col[c]
            if not arrs:
                continue
            out[c] = _to_host(
                _combine_arrow(arrs), ts.column_type(c)
            )
        return n_total, out

    def _read_pruned(self, schema, table, columns, domains):
        """Back-compat shim: whole-table domain-pruned read."""
        return self.scan(schema, table, columns, domains=domains)


def _combine_arrow(arrs):
    """Chunked/plain arrow arrays -> one contiguous Array."""
    import pyarrow as pa

    chunks = []
    for a in arrs:
        if isinstance(a, pa.ChunkedArray):
            chunks.extend(a.chunks)
        else:
            chunks.append(a)
    if len(chunks) == 1:
        return chunks[0]
    return pa.chunked_array(chunks).combine_chunks()


def _const_column(value, t: T.DataType, n: int):
    """Synthesize a partition column as n copies of its value."""
    if isinstance(t, T.VarcharType):
        out = np.empty(n, dtype=object)
        out[:] = value
        return out
    return np.full(n, value, dtype=t.np_dtype)


def _empty_host(t: T.DataType):
    if isinstance(t, T.VarcharType):
        return np.empty(0, dtype=object)
    if isinstance(t, T.DecimalType) and t.is_long:
        return np.empty((0, 2), dtype=np.int64)
    return np.empty(0, dtype=t.np_dtype)


def _concat_pieces(pieces, columns, ts: TableSchema):
    """Stitch per-file host fragments into one (values, valid|None)
    dict, preserving global row order (pieces arrive ordered)."""
    if not pieces:
        return {c: _empty_host(ts.column_type(c)) for c in columns}
    if len(pieces) == 1:
        n, cols = pieces[0]
        return {c: cols[c] for c in columns}
    out = {}
    for c in columns:
        vals_parts = []
        valid_parts = []
        any_null = False
        for n, cols in pieces:
            v = cols[c]
            if isinstance(v, tuple):
                vals, valid = v
                if valid is None:
                    valid = np.ones(len(vals), dtype=bool)
                else:
                    any_null = True
            else:
                vals, valid = v, np.ones(len(v), dtype=bool)
            vals_parts.append(vals)
            valid_parts.append(valid)
        vals = np.concatenate(vals_parts)
        if any_null:
            out[c] = (vals, np.concatenate(valid_parts))
        else:
            out[c] = vals
    return out


def _stat_to_storage(v, t: T.DataType):
    """Parquet footer statistic -> the engine's storage domain (days
    for dates, unscaled ints for decimals, micros for timestamps)."""
    import datetime
    import decimal

    if v is None:
        return None
    if isinstance(t, T.DateType) and isinstance(v, datetime.date):
        return (v - datetime.date(1970, 1, 1)).days
    if isinstance(t, T.TimestampType) and isinstance(v, datetime.datetime):
        epoch = datetime.datetime(1970, 1, 1)
        return int((v - epoch).total_seconds() * 1_000_000)
    if isinstance(t, T.DecimalType):
        if isinstance(v, decimal.Decimal):
            return int(v.scaleb(t.scale))
        return int(decimal.Decimal(str(v)).scaleb(t.scale))
    return v


def _to_host(arr, t: T.DataType):
    """Arrow array -> (values, valid|None) in the engine's host layout."""
    valid = None
    if arr.null_count:
        valid = np.asarray(arr.is_valid())
    if isinstance(t, T.VarcharType):
        vals = np.asarray(
            ["" if v is None else v for v in arr.to_pylist()], dtype=object
        )
    elif isinstance(t, T.DecimalType) and t.is_long:
        import pyarrow as pa

        # two-limb [n, 2] int64: hi = unscaled >> 32 (floor), lo = low
        # 32 bits — the engine's decimal(38) device layout
        unscaled = arr.cast(pa.decimal128(38, t.scale))
        vals = np.zeros((len(arr), 2), dtype=np.int64)
        for i, v in enumerate(unscaled.to_pylist()):
            if v is None:
                continue
            u = int(v.scaleb(t.scale))
            vals[i, 0] = u >> 32
            vals[i, 1] = u & 0xFFFFFFFF
    elif isinstance(t, T.DecimalType):
        import pyarrow as pa

        unscaled = arr.cast(pa.decimal128(38, t.scale))
        vals = np.asarray(
            [0 if v is None else int(v.scaleb(t.scale)) for v in
             unscaled.to_pylist()],
            dtype=np.int64,
        )
    elif isinstance(t, T.DateType):
        import pyarrow as pa

        vals = np.asarray(arr.cast(pa.int32()).fill_null(0))
    elif isinstance(t, T.TimestampType):
        import pyarrow as pa

        vals = np.asarray(
            # safe=False: truncate sub-microsecond units (ns files)
            # like the reference rather than raising
            arr.cast(pa.timestamp("us"), safe=False)
            .cast(pa.int64()).fill_null(0)
        )
    else:
        vals = np.asarray(arr.fill_null(0) if arr.null_count else arr)
    return vals if valid is None else (vals, valid)


def _columns_to_arrow(table_schema: TableSchema, columns: dict, sel=None):
    """Host columns -> (arrays, names) for the columns present in
    ``table_schema``, optionally row-selected by boolean mask ``sel``."""
    pa, _ = _arrow()
    arrays = []
    names = []
    for c, t in table_schema.columns:
        vals = columns[c]
        valid = None
        if isinstance(vals, tuple):
            vals, valid = vals
        vals = np.asarray(vals)
        if sel is not None:
            vals = vals[sel]
            valid = None if valid is None else np.asarray(valid)[sel]
        mask = None if valid is None else ~np.asarray(valid, dtype=bool)
        if isinstance(t, T.VarcharType):
            arr = pa.array(list(vals), type=pa.string(), mask=mask)
        elif isinstance(t, T.DecimalType):
            import decimal

            if t.is_long and vals.ndim == 2:
                # two-limb [n, 2] input: unscaled = hi * 2^32 + lo
                py = [
                    decimal.Decimal(
                        int(v[0]) * (1 << 32) + int(v[1])
                    ).scaleb(-t.scale)
                    for v in vals
                ]
            else:
                py = [
                    decimal.Decimal(int(v)).scaleb(-t.scale) for v in vals
                ]
            arr = pa.array(
                py, type=pa.decimal128(t.precision, t.scale), mask=mask
            )
        elif isinstance(t, T.DateType):
            arr = pa.array(
                np.asarray(vals, dtype=np.int32), type=pa.date32(), mask=mask
            )
        elif isinstance(t, T.TimestampType):
            arr = pa.array(
                np.asarray(vals, dtype=np.int64),
                type=pa.timestamp("us"), mask=mask,
            )
        else:
            arr = pa.array(vals, mask=mask)
        arrays.append(arr)
        names.append(c)
    return arrays, names


def _write_file(
    path: str, file_schema: TableSchema, columns: dict,
    row_group_size: int | None = None, sel=None, fsync: bool = False,
):
    """Encode host columns into ONE parquet file — the single encoder
    shared by the legacy export helper and the WriteSink path."""
    pa, pq = _arrow()
    kw = {} if row_group_size is None else {"row_group_size": row_group_size}
    arrays, names = _columns_to_arrow(file_schema, columns, sel=sel)
    pq.write_table(pa.Table.from_arrays(arrays, names=names), path, **kw)
    if fsync:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


class _ParquetSink(WriteSink):
    """Per-task parquet page sink: buffers rows per partition tuple
    and flushes part files under the staging epoch dir. Nothing lands
    in the table tree until ``ParquetConnector.finish_write`` renames
    the winning fragments in."""

    #: buffered rows per partition tuple that trigger a part-file
    #: flush (the "row-group-sized part files" unit; row_group_size,
    #: when set, additionally shapes row groups INSIDE a file)
    FLUSH_ROWS = 1 << 20

    def __init__(self, root: str, handle: dict, ctx: dict | None = None):
        super().__init__(handle)
        ctx = ctx or {}
        self.root = root
        self.epoch = str(ctx.get("epoch") or "local")
        self.task = str(ctx.get("task") or "t0")
        self.attempt = int(ctx.get("attempt") or 0)
        self.staging = os.path.join(
            root, handle["schema"], f"_tmp_{self.epoch}", handle["table"]
        )
        pb = list(handle.get("partition_by") or [])
        self.partition_by = pb
        cols = [(c, T.type_from_name(t)) for c, t in handle["columns"]]
        self.table_schema = TableSchema(handle["table"], cols)
        self.file_schema = TableSchema(
            handle["table"], [(c, t) for c, t in cols if c not in pb]
        )
        self.row_group_size = handle.get("row_group_size")
        #: partition tuple -> {col: ([values], valid list)}
        self._buf: dict[tuple, dict] = {}
        self._buf_rows: dict[tuple, int] = {}
        self._buf_bytes: dict[tuple, int] = {}
        self._seq = 0
        self._frags: list[dict] = []

    def append(self, columns: dict, n_rows: int):
        if not n_rows:
            return
        if self.partition_by:
            pvals = []
            for k in self.partition_by:
                vals, valid = columns[k]
                if valid is not None and not np.asarray(valid).all():
                    raise ValueError(
                        f"NULL value in partition column {k!r}"
                    )
                pvals.append(np.asarray(vals).tolist())
            keys = list(zip(*pvals))
        else:
            keys = [()] * n_rows
        for combo in dict.fromkeys(keys):
            sel = np.fromiter(
                (key == combo for key in keys), dtype=bool, count=n_rows
            )
            buf = self._buf.get(combo)
            if buf is None:
                buf = self._buf[combo] = {
                    c: ([], []) for c, _t in self.file_schema.columns
                }
                self._buf_rows[combo] = 0
                self._buf_bytes[combo] = 0
            k = int(sel.sum())
            for c, _t in self.file_schema.columns:
                vals, valid = columns[c]
                vals = np.asarray(vals)[sel]
                buf[c][0].extend(vals.tolist())
                buf[c][1].extend(
                    [True] * k if valid is None
                    else np.asarray(valid, dtype=bool)[sel].tolist()
                )
                b = _approx_col_bytes(vals)
                self._buf_bytes[combo] += b
                self.buffered_bytes += b
            self._buf_rows[combo] += k
            if self._buf_rows[combo] >= self.FLUSH_ROWS:
                self._flush(combo)
        self.rows_written += n_rows

    def _flush(self, combo: tuple):
        import zlib

        buf = self._buf.pop(combo)
        n = self._buf_rows.pop(combo)
        self.buffered_bytes = max(
            self.buffered_bytes - self._buf_bytes.pop(combo, 0), 0
        )
        if not n:
            return
        segs = [
            f"{k}={v}" for k, v in zip(self.partition_by, combo)
        ]
        for s in segs:
            if os.sep in s or s.startswith("_tmp"):
                raise ValueError(f"unsafe partition path segment {s!r}")
        d = os.path.join(self.staging, *segs)
        os.makedirs(d, exist_ok=True)
        # the epoch in the name keeps successive writes into one table
        # from colliding (same task ids every statement); task+attempt
        # keep speculated twins of one epoch apart
        name = (
            f"part-{self.epoch}-{self.task}-a{self.attempt}"
            f"-{self._seq:04d}.parquet"
        )
        self._seq += 1
        path = os.path.join(d, name)
        cols = {
            c: (buf[c][0], _valid_arr(buf[c][1]))
            for c, _t in self.file_schema.columns
        }
        _write_file(
            path, self.file_schema, cols,
            row_group_size=self.row_group_size, fsync=True,
        )
        with open(path, "rb") as f:
            data = f.read()
        crc = zlib.crc32(data) & 0xFFFFFFFF
        _, pq = _arrow()
        md = pq.ParquetFile(path).metadata
        stats = _footer_bounds(md, self.file_schema)
        self._frags.append({
            "path": os.path.join(*segs, name) if segs else name,
            "rows": n,
            "bytes": len(data),
            "crc": crc,
            "partition": dict(zip(self.partition_by, combo)),
            "stats": stats,
        })
        self.bytes_written += len(data)
        self.files_written += 1

    def finish(self) -> list[str]:
        import json

        for combo in list(self._buf):
            self._flush(combo)
        self.buffered_bytes = 0
        return [json.dumps(fr) for fr in self._frags]

    def abort(self):
        """Buffered pages drop here; already-staged part files are
        swept with the epoch dir by finish_write/abort_write."""
        self._buf.clear()
        self._buf_rows.clear()
        self.buffered_bytes = 0


def _valid_arr(flags: list):
    a = np.asarray(flags, dtype=bool)
    return None if a.all() else a


def _approx_col_bytes(vals: np.ndarray) -> int:
    if vals.dtype != object:
        return int(vals.nbytes)
    return sum(len(str(v)) + 8 for v in vals.tolist())


def _footer_bounds(md, file_schema: TableSchema) -> dict:
    """Merged per-column (lo, hi) storage-domain bounds from the
    footer of one written file (the fragment's stats payload)."""
    out: dict[str, list] = {}
    if not md.num_row_groups:
        return out
    name_to_idx = {
        md.row_group(0).column(j).path_in_schema: j
        for j in range(md.row_group(0).num_columns)
    }
    for i in range(md.num_row_groups):
        rg = md.row_group(i)
        for cname, j in name_to_idx.items():
            st = rg.column(j).statistics
            if st is None or not st.has_min_max:
                continue
            try:
                t = file_schema.column_type(cname)
            except KeyError:
                continue
            lo = _stat_to_storage(st.min, t)
            hi = _stat_to_storage(st.max, t)
            if isinstance(lo, bytes) or isinstance(hi, bytes):
                continue  # keep fragments JSON-safe
            cur = out.get(cname)
            if cur is None:
                out[cname] = [lo, hi]
            else:
                cur[0] = min(cur[0], lo)
                cur[1] = max(cur[1], hi)
    return out


def _manifest_entry(fr: dict) -> dict:
    return {
        "path": fr["path"], "rows": int(fr["rows"]),
        "bytes": int(fr["bytes"]), "crc": int(fr["crc"]),
    }


def _fsync_dir(path: str):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_parquet_table(
    root: str, schema: str, table: str, table_schema: TableSchema,
    columns: dict, row_group_size: int | None = None,
    partition_by: list[str] | None = None,
):
    """Write host columns as parquet (the export half of the ingest
    path; the reference writes via ParquetWriter).

    Without ``partition_by``: one file ``root/schema/table.parquet``.
    With it: a Hive-style tree ``root/schema/table/<key>=<value>/
    part-*.parquet``, one file per distinct partition tuple, with the
    partition columns elided from the files (they live in the path).
    Both shapes route through the WriteSink encoder; the partitioned
    shape additionally exercises the stage-then-commit path, so every
    partitioned fixture in the tree is built by the same machinery a
    distributed CTAS uses."""
    if not partition_by:
        os.makedirs(os.path.join(root, schema), exist_ok=True)
        _write_file(
            os.path.join(root, schema, f"{table}.parquet"),
            table_schema, columns, row_group_size=row_group_size,
        )
        return
    conn = ParquetConnector(root)
    handle = conn.begin_create(
        schema, table, table_schema, partition_by=partition_by,
        properties=(
            None if row_group_size is None
            else {"row_group_size": row_group_size}
        ),
    )
    sink = conn.write_sink(
        handle, {"epoch": "bootstrap", "task": "t0", "attempt": 0}
    )
    norm = {}
    n = None
    for c, _t in table_schema.columns:
        v = columns[c]
        vals, valid = v if isinstance(v, tuple) else (v, None)
        vals = np.asarray(vals)
        n = len(vals) if n is None else n
        norm[c] = (vals, valid)
    sink.append(norm, n or 0)
    conn.finish_write(handle, sink.finish(), token="bootstrap")
