"""Parquet files connector: columnar file ingest to device pages.

The analog of the reference's Hive-style file connectors sitting on
lib/trino-parquet (ParquetReader,
lib/trino-parquet/.../reader/ParquetReader.java:85): a directory tree
``root/<schema>/<table>.parquet`` is exposed as catalog tables; scans
read only the projected columns (projection pushdown into the arrow
reader), nulls become validity masks, decimals become unscaled int64,
dates become int32 days — the engine's device page layout.

Row counts come from file metadata without touching data pages, the
footer-stats analog of the reference's stripe/rowgroup pruning.
"""

from __future__ import annotations

import os

import numpy as np

from trino_tpu import types as T
from trino_tpu.connectors.base import Connector, Split, TableSchema

__all__ = ["ParquetConnector", "write_parquet_table"]


def _arrow():
    import pyarrow
    import pyarrow.parquet as pq

    return pyarrow, pq


def _type_from_arrow(t) -> T.DataType:
    import pyarrow as pa

    if pa.types.is_boolean(t):
        return T.BOOLEAN
    if pa.types.is_int8(t):
        return T.TINYINT
    if pa.types.is_int16(t):
        return T.SMALLINT
    if pa.types.is_int32(t):
        return T.INTEGER
    if pa.types.is_int64(t):
        return T.BIGINT
    if pa.types.is_float32(t):
        return T.REAL
    if pa.types.is_float64(t):
        return T.DOUBLE
    if pa.types.is_decimal(t):
        if t.precision > 18:
            raise NotImplementedError(
                f"decimal precision {t.precision} > 18"
            )
        return T.DecimalType(t.precision, t.scale)
    if pa.types.is_date32(t):
        return T.DATE
    if pa.types.is_timestamp(t):
        if t.tz is not None:
            raise NotImplementedError(
                "timestamp with time zone is not supported yet"
            )
        return T.TIMESTAMP
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return T.VARCHAR
    raise NotImplementedError(f"parquet type {t}")


class ParquetConnector(Connector):
    #: scan() accepts ColumnDomains and prunes rowgroups by footer
    #: min/max statistics (ParquetReader's predicate pushdown,
    #: lib/trino-parquet/.../reader/ParquetReader.java:85)
    supports_domains = True

    def __init__(self, root: str):
        self.root = root
        self._schema_cache: dict[tuple[str, str], TableSchema] = {}
        #: metrics of the LAST pruned scan (tests + EXPLAIN ANALYZE —
        #: the connector Metrics SPI analog, SPI/metrics/Metrics.java)
        self.scan_metrics: dict = {}

    def _path(self, schema: str, table: str) -> str:
        return os.path.join(self.root, schema, f"{table}.parquet")

    # ---- metadata --------------------------------------------------------

    def list_schemas(self) -> list[str]:
        if not os.path.isdir(self.root):
            return []
        return sorted(
            d for d in os.listdir(self.root)
            if os.path.isdir(os.path.join(self.root, d))
        )

    def list_tables(self, schema: str) -> list[str]:
        d = os.path.join(self.root, schema)
        if not os.path.isdir(d):
            return []
        return sorted(
            f[:-8] for f in os.listdir(d) if f.endswith(".parquet")
        )

    def table_schema(self, schema: str, table: str) -> TableSchema:
        key = (schema, table)
        if key not in self._schema_cache:
            _, pq = _arrow()
            meta = pq.read_schema(self._path(schema, table))
            cols = [
                (name, _type_from_arrow(meta.field(name).type))
                for name in meta.names
            ]
            self._schema_cache[key] = TableSchema(table, cols)
        return self._schema_cache[key]

    def row_count(self, schema: str, table: str) -> int:
        _, pq = _arrow()
        return pq.ParquetFile(self._path(schema, table)).metadata.num_rows

    # ---- scan ------------------------------------------------------------

    def scan(
        self, schema: str, table: str, columns: list[str],
        split: Split | None = None, domains=None,
    ):
        _, pq = _arrow()
        ts = self.table_schema(schema, table)
        if domains and split is None:
            tbl = self._read_pruned(schema, table, columns, domains)
        else:
            tbl = pq.read_table(
                self._path(schema, table), columns=list(columns)
            )
            if split is not None:
                tbl = tbl.slice(split.start, split.count)
        out = {}
        for c in columns:
            arr = tbl.column(c).combine_chunks()
            out[c] = _to_host(arr, ts.column_type(c))
        return out

    def _read_pruned(self, schema: str, table: str, columns, domains):
        """Read only the rowgroups whose footer min/max stats can
        intersect every column domain (stripe/rowgroup pruning,
        lib/trino-parquet predicate pushdown: a disjoint rowgroup
        cannot contribute rows — NULLs never satisfy a comparison)."""
        _, pq = _arrow()
        ts = self.table_schema(schema, table)
        pf = pq.ParquetFile(self._path(schema, table))
        md = pf.metadata
        name_to_idx = {
            md.row_group(0).column(j).path_in_schema: j
            for j in range(md.row_group(0).num_columns)
        } if md.num_row_groups else {}
        keep = []
        for i in range(md.num_row_groups):
            rg = md.row_group(i)
            skip = False
            for cname, dom in domains.items():
                j = name_to_idx.get(cname)
                if j is None:
                    continue
                st = rg.column(j).statistics
                if st is None or not st.has_min_max:
                    continue
                t = ts.column_type(cname)
                lo = _stat_to_storage(st.min, t)
                hi = _stat_to_storage(st.max, t)
                if dom.disjoint(lo, hi):
                    skip = True
                    break
            if not skip:
                keep.append(i)
        self.scan_metrics = {
            "rowgroups_total": md.num_row_groups,
            "rowgroups_read": len(keep),
        }
        import pyarrow as pa

        if not keep:
            return pa.schema(
                [(c, pf.schema_arrow.field(c).type) for c in columns]
            ).empty_table()
        return pf.read_row_groups(keep, columns=list(columns))


def _stat_to_storage(v, t: T.DataType):
    """Parquet footer statistic -> the engine's storage domain (days
    for dates, unscaled ints for decimals, micros for timestamps)."""
    import datetime
    import decimal

    if v is None:
        return None
    if isinstance(t, T.DateType) and isinstance(v, datetime.date):
        return (v - datetime.date(1970, 1, 1)).days
    if isinstance(t, T.TimestampType) and isinstance(v, datetime.datetime):
        epoch = datetime.datetime(1970, 1, 1)
        return int((v - epoch).total_seconds() * 1_000_000)
    if isinstance(t, T.DecimalType):
        if isinstance(v, decimal.Decimal):
            return int(v.scaleb(t.scale))
        return int(decimal.Decimal(str(v)).scaleb(t.scale))
    return v


def _to_host(arr, t: T.DataType):
    """Arrow array -> (values, valid|None) in the engine's host layout."""
    valid = None
    if arr.null_count:
        valid = np.asarray(arr.is_valid())
    if isinstance(t, T.VarcharType):
        vals = np.asarray(
            ["" if v is None else v for v in arr.to_pylist()], dtype=object
        )
    elif isinstance(t, T.DecimalType):
        import pyarrow as pa

        unscaled = arr.cast(pa.decimal128(38, t.scale))
        vals = np.asarray(
            [0 if v is None else int(v.scaleb(t.scale)) for v in
             unscaled.to_pylist()],
            dtype=np.int64,
        )
    elif isinstance(t, T.DateType):
        import pyarrow as pa

        vals = np.asarray(arr.cast(pa.int32()).fill_null(0))
    elif isinstance(t, T.TimestampType):
        import pyarrow as pa

        vals = np.asarray(
            # safe=False: truncate sub-microsecond units (ns files)
            # like the reference rather than raising
            arr.cast(pa.timestamp("us"), safe=False)
            .cast(pa.int64()).fill_null(0)
        )
    else:
        vals = np.asarray(arr.fill_null(0) if arr.null_count else arr)
    return vals if valid is None else (vals, valid)


def write_parquet_table(
    root: str, schema: str, table: str, table_schema: TableSchema,
    columns: dict, row_group_size: int | None = None,
):
    """Write host columns as one parquet file (the export half of the
    ingest path; the reference writes via ParquetWriter)."""
    pa, pq = _arrow()
    os.makedirs(os.path.join(root, schema), exist_ok=True)
    arrays = []
    names = []
    for c, t in table_schema.columns:
        vals = columns[c]
        valid = None
        if isinstance(vals, tuple):
            vals, valid = vals
        mask = None if valid is None else ~np.asarray(valid, dtype=bool)
        if isinstance(t, T.VarcharType):
            arr = pa.array(list(vals), type=pa.string(), mask=mask)
        elif isinstance(t, T.DecimalType):
            import decimal

            py = [
                decimal.Decimal(int(v)).scaleb(-t.scale)
                for v in np.asarray(vals)
            ]
            arr = pa.array(py, type=pa.decimal128(t.precision, t.scale), mask=mask)
        elif isinstance(t, T.DateType):
            arr = pa.array(
                np.asarray(vals, dtype=np.int32), type=pa.date32(), mask=mask
            )
        elif isinstance(t, T.TimestampType):
            arr = pa.array(
                np.asarray(vals, dtype=np.int64),
                type=pa.timestamp("us"), mask=mask,
            )
        else:
            arr = pa.array(np.asarray(vals), mask=mask)
        arrays.append(arr)
        names.append(c)
    kw = {} if row_group_size is None else {"row_group_size": row_group_size}
    pq.write_table(
        pa.Table.from_arrays(arrays, names=names),
        os.path.join(root, schema, f"{table}.parquet"),
        **kw,
    )
