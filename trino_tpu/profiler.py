"""Per-operator profiler: host-side wall-clock attribution joined
with XLA cost analysis.

The reference engine answers "where is this query's time going?" at
operator granularity — OperatorStats hang off every task and roll up
through TaskInfo/StageInfo into the QueryInfo tree
(MAIN/operator/OperatorStats.java). Here the operator is the unit the
executor actually dispatches: a fused FUSABLE chain compiles to ONE
XLA program and therefore profiles as ONE operator (its label names
the whole chain, e.g. ``Filter→Aggregate``); joins, scans and
exchanges profile individually through the same ``execute`` hook.

The TPU-native half: each compiled chain's executable has an XLA cost
model (``compiled.cost_analysis()`` — FLOPs and bytes accessed), so a
record's measured wall time converts into achieved GFLOP/s and an
achieved-vs-roofline utilization. Cost analysis is computed LAZILY per
jit-cache key on first request: the hot dispatch path only stores the
abstract avals; the one extra ``lower().compile()`` resolves through
the persistent XLA cache as a deserialize, not a recompile.

Profiling adds no device syncs: row counts come from ``known_rows``
when the executor already synced (deferred-sync pages report None) and
byte counts come from array shape metadata.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

__all__ = [
    "OperatorProfiler", "OpRecord", "peak_rates", "roofline",
    "attach_roofline", "tree_from_stats",
]

#: (peak GFLOP/s, peak GB/s) per jax backend — deliberately coarse
#: defaults; deployments set TRINO_TPU_PEAK_GFLOPS/_PEAK_GBPS to the
#: part they actually run on (v4 fp32, v5e bf16, ...)
_BACKEND_PEAKS = {
    "tpu": (275_000.0, 1_200.0),
    "gpu": (19_500.0, 900.0),
    "cpu": (150.0, 50.0),
}


def peak_rates() -> tuple[float, float]:
    """(peak_gflops, peak_gbps) for the roofline ceiling: env
    overrides first, then the backend default table."""
    try:
        import jax

        backend = jax.default_backend()
    except Exception:  # pragma: no cover - jax always importable here
        backend = "cpu"
    gflops, gbps = _BACKEND_PEAKS.get(backend, _BACKEND_PEAKS["cpu"])
    gflops = float(os.environ.get("TRINO_TPU_PEAK_GFLOPS", gflops))
    gbps = float(os.environ.get("TRINO_TPU_PEAK_GBPS", gbps))
    return gflops, gbps


def roofline(flops: float, bytes_accessed: float, wall_ms: float) -> dict:
    """Roofline attribution for one record: achieved GFLOP/s against
    min(compute ceiling, bandwidth ceiling × arithmetic intensity)."""
    if not flops or not wall_ms or wall_ms <= 0:
        return {}
    peak_gflops, peak_gbps = peak_rates()
    achieved = flops / (wall_ms * 1e-3) / 1e9
    out = {"achieved_gflops": round(achieved, 3)}
    if bytes_accessed:
        intensity = flops / bytes_accessed
        ceiling = min(peak_gflops, peak_gbps * intensity)
        out["intensity_flops_per_byte"] = round(intensity, 3)
        out["roofline_gflops"] = round(ceiling, 3)
        if ceiling > 0:
            out["roofline_utilization"] = round(achieved / ceiling, 4)
    return out


@dataclass
class OpRecord:
    op_id: int
    parent_id: int | None
    name: str
    node_type: str
    plan_node_id: int  # id(plan node) — EXPLAIN ANALYZE joins on it
    start_s: float
    wall_ms: float = 0.0
    self_ms: float = 0.0
    rows_out: int | None = None
    bytes_out: int | None = None
    flops: float = 0.0
    bytes_accessed: float = 0.0
    child_ids: list = field(default_factory=list)
    dispatch_keys: list = field(default_factory=list)
    dispatches: int = 0

    def to_dict(self) -> dict:
        d = {
            "op_id": self.op_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "node_type": self.node_type,
            "wall_ms": round(self.wall_ms, 3),
            "self_ms": round(self.self_ms, 3),
            "rows_out": self.rows_out,
            "bytes_out": self.bytes_out,
            "dispatches": self.dispatches,
        }
        if self.flops:
            d["flops"] = self.flops
            d["bytes_accessed"] = self.bytes_accessed
            d.update(roofline(self.flops, self.bytes_accessed, self.self_ms))
        return d


def _page_nbytes(page) -> int | None:
    """Device bytes of a page from shape metadata only (no sync)."""
    try:
        total = 0
        for c in page.columns:
            data = getattr(c, "data", None)
            if data is not None and hasattr(data, "nbytes"):
                total += int(data.nbytes)
            valid = getattr(c, "valid", None)
            if valid is not None and hasattr(valid, "nbytes"):
                total += int(valid.nbytes)
        return total
    except Exception:
        return None


class OperatorProfiler:
    """Stack-based operator timer an executor carries for one query
    (or one fleet task). ``LocalExecutor.execute`` opens a record per
    dispatched operator; recursion through ``self.execute`` nests
    children, so the stack reconstructs the operator tree without the
    profiler knowing anything about plan shapes."""

    def __init__(self):
        self.records: list[OpRecord] = []
        self._stack: list[OpRecord] = []
        self._seq = 0
        self._costs_resolved = False

    # -- executor-facing hooks ------------------------------------------

    def open(self, name: str, node_type: str, plan_node_id: int) -> OpRecord:
        rec = OpRecord(
            op_id=self._seq,
            parent_id=self._stack[-1].op_id if self._stack else None,
            name=name,
            node_type=node_type,
            plan_node_id=plan_node_id,
            start_s=time.perf_counter(),
        )
        self._seq += 1
        if self._stack:
            self._stack[-1].child_ids.append(rec.op_id)
        self.records.append(rec)
        self._stack.append(rec)
        return rec

    def close(self, rec: OpRecord, page=None) -> None:
        rec.wall_ms = (time.perf_counter() - rec.start_s) * 1e3
        while self._stack and self._stack[-1] is not rec:
            self._stack.pop()  # exception unwound through children
        if self._stack:
            self._stack.pop()
        if page is not None:
            known = getattr(page, "known_rows", None)
            if known is not None:
                rec.rows_out = int(known)
            rec.bytes_out = _page_nbytes(page)

    def note_dispatch(self, key) -> None:
        """Called by ``_dispatch_chain`` with the jit-cache key it just
        ran — the handle for lazy XLA cost analysis at finish time."""
        if self._stack:
            top = self._stack[-1]
            top.dispatches += 1
            if key not in top.dispatch_keys:
                top.dispatch_keys.append(key)

    # -- results --------------------------------------------------------

    def finish(self, executor=None) -> list[dict]:
        """Seal records: compute self time (wall minus direct
        children), resolve XLA costs through the executor's lazy
        cost cache, and return JSON-safe operator_stats rows."""
        by_id = {r.op_id: r for r in self.records}
        for rec in self.records:
            child_ms = sum(by_id[c].wall_ms for c in rec.child_ids)
            rec.self_ms = max(rec.wall_ms - child_ms, 0.0)
        if executor is not None and not self._costs_resolved:
            # one-shot: finish() may be called again (timing-only seal
            # then a lazy profile resolve) without double-counting
            self._costs_resolved = True
            for rec in self.records:
                for key in rec.dispatch_keys:
                    cost = executor.chain_cost(key)
                    if cost:
                        rec.flops += cost.get("flops", 0.0)
                        rec.bytes_accessed += cost.get(
                            "bytes_accessed", 0.0
                        )
        return [r.to_dict() for r in self.records]

    def record_for(self, plan_node_id: int) -> OpRecord | None:
        """Latest record for a plan node (EXPLAIN ANALYZE join)."""
        for rec in reversed(self.records):
            if rec.plan_node_id == plan_node_id:
                return rec
        return None


def attach_roofline(stats: list[dict]) -> list[dict]:
    """Fill roofline fields on operator_stats rows that carry raw
    flops/bytes but were serialized before attribution (cross-process
    arrivals where the env-configured peaks differ coordinator-side)."""
    for row in stats:
        if row.get("flops") and "achieved_gflops" not in row:
            row.update(
                roofline(
                    row["flops"],
                    row.get("bytes_accessed", 0.0),
                    row.get("self_ms", 0.0),
                )
            )
    return stats


def tree_from_stats(stats: list[dict]) -> list[dict]:
    """Re-nest a flat operator_stats list (parent_id links) into the
    operator tree used by QueryInfo JSON. Rows arrive JSON-safe from
    workers; the nesting is rebuilt coordinator-side."""
    nodes = {row["op_id"]: dict(row, children=[]) for row in stats}
    roots = []
    for row in stats:
        node = nodes[row["op_id"]]
        parent = row.get("parent_id")
        if parent is not None and parent in nodes:
            nodes[parent]["children"].append(node)
        else:
            roots.append(node)
    return roots
