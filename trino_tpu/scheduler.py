"""Event-driven partition-granular stage scheduler.

The fleet's barrier scheduler admits a consumer stage only after its
producer stage has FULLY committed — every consumer head waits for the
slowest producer tail. This module is the EventDriven-scheduler analog
(the reference's speculative, partition-granular FTE admission,
MAIN/execution/scheduler/faulttolerant/EventDrivenFaultTolerantQueryScheduler.java;
same direction as morsel-driven parallelism: a DAG edge is a
per-partition data dependency, not a stage-level barrier):

* producers commit per-partition ``-p{part}.done`` markers as each
  partition file lands (exec/spool.py) and report the committed set on
  every task-status poll;
* :class:`EventDrivenScheduler` folds those ``(stage, task, attempt,
  partition)`` events and admits an aligned consumer task the moment
  its specific input partition is committed across ALL producer tasks;
* each admission pins the exact producer attempts the coordinator
  observed, so a consumer never mixes attempts when a speculative or
  retried producer commits a different attempt later (any CRC-valid
  committed attempt of a deterministic task carries identical bytes,
  so reading a pinned non-winning attempt is still correct);
* quarantining a producer attempt retracts its partition commits and
  rescinds the in-flight admissions that depended on them.

Readiness rules (``task_ready``):

* ``BARRIER`` mode — every input stage fully complete (the legacy
  behavior, preserved as fallback and for A/B benching via the
  ``stage_admission`` session property);
* ``PIPELINED`` mode — for each input edge: a fully complete input
  stage is always satisfied; an ``aligned`` edge into a partitioned
  consumer task ``p`` is satisfied once every producer task has
  committed partition ``p`` (or fully committed — the only way an
  EMPTY partition, which writes no marker, becomes observable); any
  other edge (``all``-mode / broadcast, or a non-partitioned consumer
  such as a root gather) degrades to the barrier rule for that edge.
  Leaf stages have no inputs, so the DAG always has dispatchable work
  and pipelined admission cannot deadlock: a task is admitted only
  when every byte it will read is already durable.
"""

from __future__ import annotations

import time

from trino_tpu import telemetry

__all__ = ["EventDrivenScheduler"]


class EventDrivenScheduler:
    """Partition-granular admission control for one fleet DAG run.

    The FleetRunner RPC loop feeds commit events in (``on_partition_
    commit`` / ``on_task_commit`` / ``on_stage_complete``) and asks
    ``task_ready`` before dispatching a queued task; ``admit`` records
    the admission (wait histogram, overlap windows) and returns the
    per-input-stage attempt pins to ship on the stage-task request.
    Single-threaded by construction — it is only touched from the
    coordinator's ``_run_dag`` loop."""

    def __init__(
        self, stages, mode: str = "PIPELINED", *, clock=time.monotonic,
    ):
        self.mode = str(mode).upper()
        self._clock = clock
        self._by_id = {s.stage_id: s for s in stages}
        #: sid -> tid -> attempt -> committed partition ids
        self._partitions: dict[str, dict[str, dict[int, set[int]]]] = {}
        #: sid -> tid -> fully committed attempts
        self._task_commits: dict[str, dict[str, set[int]]] = {}
        self._complete: set[str] = set()
        #: sid -> registered task ids, in spec order (read-order law:
        #: consumers concatenate producer payloads in this order, so
        #: BARRIER and PIPELINED return byte-identical results)
        self._specs: dict[str, list[str]] = {}
        self._queued_at: dict[str, float] = {}
        self._admitted_at: dict[str, float] = {}
        self._admission_wait_ms: dict[str, float] = {}
        #: (producer sid, tid, attempt) -> consumer tids pinned to it
        self._dependents: dict[tuple[str, str, int], set[str]] = {}
        #: (producer sid, tid, attempt) -> worker URI whose buffer pool
        #: holds the attempt's output (the direct-exchange residency
        #: hint shipped on consumer stage-task requests)
        self._locations: dict[tuple[str, str, int], str] = {}
        #: open overlap windows: (consumer tid, producer sid, t_admit)
        self._overlap_open: list[tuple[str, str, float]] = []
        self._overlap_s = 0.0
        self.admissions = 0
        self.rescinds = 0

    # ---- commit-event feed -------------------------------------------------

    def register_stage(self, stage, specs) -> None:
        """A stage's tasks were constructed and queued; admission-wait
        clocks start now."""
        self._specs[stage.stage_id] = [s.task_id for s in specs]
        now = self._clock()
        for s in specs:
            self._queued_at.setdefault(s.task_id, now)

    def on_partition_commit(
        self, sid: str, tid: str, attempt: int, part: int,
        worker: str | None = None,
    ) -> None:
        self._partitions.setdefault(sid, {}).setdefault(
            tid, {}
        ).setdefault(int(attempt), set()).add(int(part))
        if worker:
            self._locations[(sid, tid, int(attempt))] = worker

    def on_task_commit(
        self, sid: str, tid: str, attempt: int,
        worker: str | None = None,
    ) -> None:
        self._task_commits.setdefault(sid, {}).setdefault(
            tid, set()
        ).add(int(attempt))
        if worker:
            self._locations[(sid, tid, int(attempt))] = worker

    def on_stage_complete(self, sid: str) -> None:
        """Close the overlap windows of consumers admitted while this
        producer was still streaming: that span IS the pipelining win."""
        self._complete.add(sid)
        now = self._clock()
        still = []
        for (tid, psid, t0) in self._overlap_open:
            if psid == sid:
                self._overlap_s += max(0.0, now - t0)
            else:
                still.append((tid, psid, t0))
        self._overlap_open = still

    def retract(self, sid: str, tid: str, attempt: int) -> list[str]:
        """A producer attempt was quarantined: drop its commit records
        and return the consumer tasks whose admission pinned it (the
        fleet cancels + requeues the non-finished ones; a FINISHED
        consumer already CRC-verified every byte it read, and the
        producer is deterministic, so its output stands)."""
        attempt = int(attempt)
        self._partitions.get(sid, {}).get(tid, {}).pop(attempt, None)
        self._task_commits.get(sid, {}).get(tid, set()).discard(attempt)
        self._complete.discard(sid)
        self._locations.pop((sid, tid, attempt), None)
        return sorted(self._dependents.pop((sid, tid, attempt), ()))

    # ---- readiness + admission --------------------------------------------

    def task_ready(self, stage, spec) -> bool:
        if self.mode != "PIPELINED":
            return all(
                i.stage_id in self._complete for i in stage.inputs
            )
        for i in stage.inputs:
            if i.stage_id in self._complete:
                continue
            if i.mode != "aligned" or spec.partition is None:
                return False  # barrier edge (broadcast / gather)
            ptids = self._specs.get(i.stage_id)
            if not ptids:
                return False
            for ptid in ptids:
                if self._pin_attempt(
                    i.stage_id, ptid, spec.partition
                ) is None:
                    return False
        return True

    def _pin_attempt(
        self, sid: str, ptid: str, part: int | None
    ) -> int | None:
        """Attempt to pin for one producer task: smallest fully
        committed attempt, else (for a specific partition) the
        smallest attempt holding that partition's marker."""
        commits = self._task_commits.get(sid, {}).get(ptid)
        if commits:
            return min(commits)
        if part is None:
            return None
        by_attempt = self._partitions.get(sid, {}).get(ptid, {})
        cands = [a for a, ps in by_attempt.items() if part in ps]
        return min(cands) if cands else None

    def pins_for(self, stage, spec) -> dict | None:
        """Per-input-stage source pins for a stage-task request:
        ``{sid: {"task_ids": [...], "attempts": {tid: attempt}}}``.
        ``task_ids`` always carries the registered spec order;
        ``attempts`` is included only when every producer task is
        pinnable (otherwise the worker falls back to attempt-level
        dedup, which needs the stage complete). Returns None in
        BARRIER mode — the legacy wire format stays untouched."""
        if self.mode != "PIPELINED":
            return None
        pins = {}
        for i in stage.inputs:
            sid = i.stage_id
            ptids = self._specs.get(sid)
            if not ptids:
                return None  # producer not registered yet; cannot post
            entry: dict = {"task_ids": list(ptids)}
            part = spec.partition if i.mode == "aligned" else None
            attempts = {}
            for ptid in ptids:
                a = self._pin_attempt(sid, ptid, part)
                if a is None:
                    attempts = None
                    break
                attempts[ptid] = a
            if attempts is not None:
                entry["attempts"] = attempts
                # best-effort direct-exchange residency hints: the
                # worker whose buffer pool holds each pinned attempt's
                # output (consumers without a hint, or whose fetch
                # misses, read the spool — correctness never depends
                # on this map)
                workers = {
                    ptid: self._locations[(sid, ptid, a)]
                    for ptid, a in attempts.items()
                    if (sid, ptid, a) in self._locations
                }
                if workers:
                    entry["workers"] = workers
            pins[sid] = entry
        return pins

    def admit(self, stage, spec) -> dict | None:
        """Record a dispatch of ``spec`` (first admission only for the
        wait/overlap books; re-posts and speculative attempts reuse
        it) and return the source pins for the request."""
        tid = spec.task_id
        now = self._clock()
        if tid not in self._admitted_at:
            self._admitted_at[tid] = now
            wait = max(0.0, now - self._queued_at.get(tid, now))
            self._admission_wait_ms[tid] = wait * 1e3
            self.admissions += 1
            telemetry.SCHED_ADMISSIONS.inc(mode=self.mode)
            telemetry.SCHED_ADMISSION_WAIT.observe(wait, mode=self.mode)
            for i in stage.inputs:
                if i.stage_id not in self._complete:
                    self._overlap_open.append((tid, i.stage_id, now))
        pins = self.pins_for(stage, spec)
        if pins:
            for psid, entry in pins.items():
                for ptid, a in (entry.get("attempts") or {}).items():
                    self._dependents.setdefault(
                        (psid, ptid, int(a)), set()
                    ).add(tid)
        return pins

    def ready_count(self, queues, by_id, eligible_at, now) -> int:
        """How many queued specs are dispatchable RIGHT NOW — ready on
        their input edges and past any retry backoff. Serving-mode
        dispatch keeps exactly this many slot tickets outstanding with
        the shared Dispatcher (its "want"), so a query never holds
        fleet capacity for work it cannot yet post."""
        n = 0
        for sid, q in queues.items():
            stage = by_id[sid]
            for sp in q:
                if (
                    now >= eligible_at.get(sp.task_id, 0.0)
                    and self.task_ready(stage, sp)
                ):
                    n += 1
        return n

    # ---- read-side surfaces ------------------------------------------------

    def admission_wait_ms(self, tid: str) -> float:
        return float(self._admission_wait_ms.get(tid, 0.0))

    def pinned_workers(self) -> set:
        """Worker URIs some committed attempt's output currently
        resides on — the membership layer's drain gate: a DRAINING
        worker may not deregister while a live query could still
        fetch one of these buffers (retract/quarantine removes the
        entry; query end drops the whole scheduler)."""
        return set(self._locations.values())

    def overlap_seconds(self) -> float:
        """Total producer/consumer overlap won so far (closed windows
        only; all windows close once every stage completes)."""
        return float(self._overlap_s)
