"""Plan statistics: cardinality + per-symbol value-domain estimation.

The analog of the reference's StatsCalculator stack (MAIN/cost/:
FilterStatsCalculator.java, JoinStatsRule.java,
AggregationStatsRule.java) collapsed into one recursive pass. Two
consumers with different contracts:

- **Cardinality** (``PlanStats.rows``, per-symbol ``ndv``) is an
  *estimate* — used for join ordering, build-side choice,
  broadcast-vs-partitioned and aggregation capacity planning. Being
  wrong costs performance, never correctness.
- **Value bounds** (``lo``/``hi`` with ``exact=True``) are
  *guarantees* — the executor packs group-by keys into
  ``bit_length(hi - lo)`` bits (value-range key packing), so a live
  row outside the claimed range would corrupt grouping. Bounds start
  from connector-exact table stats and are only narrowed by predicates
  that are *guaranteed applied* beneath the consuming node; anything
  uncertain drops exactness.

Bounds/ndv live in the column's storage order-domain: ints as-is,
dates as day numbers, decimals as unscaled ints, doubles as floats
(varchar carries ndv only).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from trino_tpu import types as T
from trino_tpu.expr.ir import Call, Cast, InputRef, Literal, RowExpression
from trino_tpu.metadata import Metadata
from trino_tpu.plan import nodes as P

__all__ = ["SymbolStats", "PlanStats", "estimate", "annotate"]

#: selectivity for predicates the calculator cannot reason about
#: (the reference's UNKNOWN_FILTER_COEFFICIENT is 0.9; 0.5 is chosen
#: because unfiltered over-estimates only waste capacity while
#: under-estimates trigger overflow retries)
UNKNOWN_FILTER_COEFFICIENT = 0.5


@dataclass(frozen=True)
class SymbolStats:
    ndv: float | None = None
    lo: float | None = None
    hi: float | None = None
    null_frac: float = 0.0
    #: True when lo/hi are guaranteed bounds (see module docstring)
    exact: bool = False

    @property
    def range_width(self) -> float | None:
        if self.lo is None or self.hi is None:
            return None
        return self.hi - self.lo


@dataclass(frozen=True)
class PlanStats:
    rows: float
    symbols: dict[str, SymbolStats] = field(default_factory=dict)

    def sym(self, name: str) -> SymbolStats:
        return self.symbols.get(name, SymbolStats())


_UNKNOWN = SymbolStats()


def estimate(
    node: P.PlanNode, metadata: Metadata, _cache: dict | None = None
) -> PlanStats:
    """Estimate output stats of ``node`` (memoized by node identity)."""
    if _cache is None:
        _cache = {}
    hit = _cache.get(id(node))
    # entries pin the node object (id-keyed caches alias freed
    # addresses otherwise) and verify identity before use
    if hit is not None and hit[0] is node:
        return hit[1]
    out = _estimate(node, metadata, _cache)
    _cache[id(node)] = (node, out)
    return out


def _estimate(node, md, cache) -> PlanStats:
    if isinstance(node, P.TableScan):
        return _scan_stats(node, md)
    if isinstance(node, P.Values):
        return PlanStats(float(len(node.rows)))
    if isinstance(node, P.Filter):
        src = estimate(node.source, md, cache)
        return _filter_stats(src, node.predicate)
    if isinstance(node, P.Project):
        src = estimate(node.source, md, cache)
        symbols = {}
        for sym, e in node.assignments.items():
            if isinstance(e, InputRef):
                symbols[sym] = src.sym(e.name)
            else:
                symbols[sym] = _expr_stats(e, src)
        return PlanStats(src.rows, symbols)
    if isinstance(node, P.Aggregate):
        return _aggregate_stats(node, md, cache)
    if isinstance(node, P.Join):
        return _join_stats(node, md, cache)
    if isinstance(node, P.SemiJoin):
        src = estimate(node.source, md, cache)
        filt = estimate(node.filter_source, md, cache)
        symbols = dict(src.symbols)
        symbols[node.match_symbol] = SymbolStats(ndv=2.0)
        # rows unchanged: the match symbol is a column; the Filter
        # above applies its selectivity (bare-boolean-ref path)
        return PlanStats(src.rows, symbols)
    if isinstance(node, P.Window):
        src = estimate(node.source, md, cache)
        symbols = dict(src.symbols)
        for sym, call in node.functions.items():
            symbols[sym] = _UNKNOWN
        return PlanStats(src.rows, symbols)
    if isinstance(node, P.Union):
        rows = 0.0
        branches = [estimate(s, md, cache) for s in node.all_sources]
        rows = sum(b.rows for b in branches)
        symbols = {}
        for sym, ins in node.symbol_map.items():
            per = [b.sym(i) for b, i in zip(branches, ins)]
            symbols[sym] = _union_sym(per)
        return PlanStats(rows, symbols)
    if isinstance(node, (P.Limit, P.TopN)):
        src = estimate(node.sources[0], md, cache)
        n = getattr(node, "count", -1)
        rows = min(float(n), src.rows) if n >= 0 else src.rows
        return PlanStats(rows, dict(src.symbols))
    if isinstance(node, (P.Sort, P.Output, P.Exchange)):
        src = estimate(node.sources[0], md, cache)
        return PlanStats(src.rows, dict(src.symbols))
    if isinstance(node, P.GroupId):
        src = estimate(node.source, md, cache)
        k = max(len(node.grouping_sets), 1)
        return PlanStats(src.rows * k, dict(src.symbols))
    if node.sources:
        src = estimate(node.sources[0], md, cache)
        return PlanStats(src.rows, {})
    return PlanStats(1.0)


def _scan_stats(node: P.TableScan, md: Metadata) -> PlanStats:
    try:
        conn = md.connector(node.catalog)
        rows = float(conn.row_count(node.schema, node.table))
    except Exception:
        return PlanStats(1e6)
    symbols = {}
    for sym, col in node.assignments.items():
        # column-by-column so generator connectors only materialize
        # what the query touches
        try:
            cs = conn.column_stats(node.schema, node.table, col)
        except Exception:
            cs = None
        if cs is None:
            symbols[sym] = _UNKNOWN
        else:
            symbols[sym] = SymbolStats(
                ndv=cs.ndv, lo=cs.lo, hi=cs.hi,
                null_frac=cs.null_fraction,
                exact=cs.lo is not None,
            )
    # pushdown domains narrow what the scan actually reads: clamp the
    # symbol bounds and scale the row estimate by the range fraction.
    # The Filter the domains came from stays in the plan and re-derives
    # its selectivity against the CLAMPED bounds (keep ~ 1), so the
    # reduction is applied once, at the scan where storage applies it.
    if node.domains:
        inv = {c: s for s, c in node.assignments.items()}
        for cname, dom in node.domains.items():
            sym = inv.get(cname)
            st = symbols.get(sym) if sym is not None else None
            if st is None or st.lo is None or st.hi is None:
                continue
            try:
                dlo = st.lo if dom[0] is None else float(dom[0])
                dhi = st.hi if dom[1] is None else float(dom[1])
            except (TypeError, ValueError):
                continue  # non-numeric domain (varchar partition key)
            nlo, nhi = max(float(st.lo), dlo), min(float(st.hi), dhi)
            if nhi < nlo:
                rows = 0.0
                continue
            width = float(st.hi) - float(st.lo)
            if width > 0:
                rows *= min(max((nhi - nlo) / width, 0.0), 1.0)
            symbols[sym] = replace(st, lo=nlo, hi=nhi)
    return PlanStats(max(rows, 1.0), symbols)


def _union_sym(per: list[SymbolStats]) -> SymbolStats:
    if any(s.ndv is None for s in per):
        return _UNKNOWN
    lo = hi = None
    exact = all(s.exact for s in per)
    if all(s.lo is not None for s in per):
        lo = min(s.lo for s in per)
        hi = max(s.hi for s in per)
    else:
        exact = False
    return SymbolStats(
        ndv=sum(s.ndv for s in per), lo=lo, hi=hi,
        null_frac=max(s.null_frac for s in per), exact=exact,
    )


# ---- filters ---------------------------------------------------------------

def _conjuncts(e: RowExpression) -> list[RowExpression]:
    if isinstance(e, Call) and e.name == "and":
        out = []
        for a in e.args:
            out.extend(_conjuncts(a))
        return out
    return [e]


def _literal_num(e: RowExpression) -> float | int | None:
    """Numeric order-domain value of a literal (unscaled for decimals,
    day number for dates)."""
    while isinstance(e, Cast):
        # a cast changes the domain (e.g. decimal rescale); only
        # identity-domain casts are safe to look through
        if not _same_domain(e.type, e.arg.type):
            return None
        e = e.arg
    if not isinstance(e, Literal) or e.value is None:
        return None
    if isinstance(e.type, T.VarcharType):
        return None
    from trino_tpu.expr.compiler import _literal_device_value

    try:
        v = _literal_device_value(e)
    except Exception:
        return None
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, int):
        return v  # keep exact: float64 rounds beyond 2^53
    if isinstance(v, float):
        return v
    return None


def _same_domain(a: T.DataType, b: T.DataType) -> bool:
    if isinstance(a, T.DecimalType) or isinstance(b, T.DecimalType):
        return (
            isinstance(a, T.DecimalType)
            and isinstance(b, T.DecimalType)
            and a.scale == b.scale
        )
    return True


def _plain_ref(e: RowExpression) -> str | None:
    if isinstance(e, InputRef):
        return e.name
    return None


def _filter_stats(src: PlanStats, predicate: RowExpression | None) -> PlanStats:
    if predicate is None:
        return src
    rows = src.rows
    symbols = dict(src.symbols)
    for c in _conjuncts(predicate):
        sel = _apply_conjunct(c, symbols)
        rows *= sel
    rows = max(rows, 1.0)
    # cap every ndv at the new row estimate
    for s, st in symbols.items():
        if st.ndv is not None and st.ndv > rows:
            symbols[s] = replace(st, ndv=max(rows, 1.0))
    return PlanStats(rows, symbols)


def _apply_conjunct(c: RowExpression, symbols: dict) -> float:
    """Selectivity of one conjunct; narrows symbol bounds in place.
    Bounds narrowed here keep ``exact=True``: a conjunct only narrows
    the symbol it directly constrains, and every surviving row
    satisfies it."""
    if isinstance(c, Call) and c.name in ("eq", "ne", "lt", "le", "gt", "ge"):
        a, b = c.args
        ra, rb = _plain_ref(a), _plain_ref(b)
        va, vb = _literal_num(a), _literal_num(b)
        if ra is not None and vb is not None:
            return _range_conjunct(c.name, ra, vb, symbols)
        if rb is not None and va is not None:
            flip = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}
            return _range_conjunct(
                flip.get(c.name, c.name), rb, va, symbols
            )
        if c.name == "eq" and ra is not None and rb is not None:
            na = symbols.get(ra, _UNKNOWN).ndv
            nb = symbols.get(rb, _UNKNOWN).ndv
            if na and nb:
                return 1.0 / max(na, nb)
        return UNKNOWN_FILTER_COEFFICIENT
    if isinstance(c, Call) and c.name == "between":
        x, lo, hi = c.args
        r = _plain_ref(x)
        vlo, vhi = _literal_num(lo), _literal_num(hi)
        if r is not None and vlo is not None and vhi is not None:
            s1 = _range_conjunct("ge", r, vlo, symbols)
            s2 = _range_conjunct("le", r, vhi, symbols)
            return s1 * s2
        return UNKNOWN_FILTER_COEFFICIENT
    if isinstance(c, Call) and c.name == "in":
        x = c.args[0]
        r = _plain_ref(x)
        vals = [_literal_num(a) for a in c.args[1:]]
        if r is not None and all(v is not None for v in vals) and vals:
            st = symbols.get(r, _UNKNOWN)
            if st.ndv:
                sel = min(1.0, len(set(vals)) / st.ndv)
            else:
                sel = UNKNOWN_FILTER_COEFFICIENT
            lo, hi = min(vals), max(vals)
            symbols[r] = replace(
                st,
                lo=lo if st.lo is None else max(st.lo, lo),
                hi=hi if st.hi is None else min(st.hi, hi),
                ndv=min(st.ndv, len(set(vals))) if st.ndv else None,
                null_frac=0.0,
            )
            return sel
        return UNKNOWN_FILTER_COEFFICIENT
    if isinstance(c, Call) and c.name == "is_null":
        r = _plain_ref(c.args[0])
        if r is not None:
            st = symbols.get(r, _UNKNOWN)
            return st.null_frac if st.ndv is not None else 0.1
        return 0.1
    if isinstance(c, Call) and c.name == "not":
        inner = c.args[0]
        if isinstance(inner, Call) and inner.name == "is_null":
            r = _plain_ref(inner.args[0])
            if r is not None:
                st = symbols.get(r, _UNKNOWN)
                symbols[r] = replace(st, null_frac=0.0)
                return 1.0 - st.null_frac
            return 0.9
        # NOT(x): bounds inside must not narrow — evaluate on a scratch
        scratch = dict(symbols)
        return max(0.0, 1.0 - _apply_conjunct(inner, scratch))
    if isinstance(c, Call) and c.name == "or":
        # independence-union; bounds must not narrow (either branch
        # may hold)
        remaining = 1.0
        for b in c.args:
            scratch = dict(symbols)
            s = _apply_conjunct(b, scratch)
            remaining *= 1.0 - s
        return min(1.0, 1.0 - remaining)
    if isinstance(c, Call) and c.name == "like":
        return 0.25
    if isinstance(c, InputRef):
        # bare boolean column (e.g. a semi-join match symbol)
        st = symbols.get(c.name, _UNKNOWN)
        if st.ndv == 2.0:
            return 0.5
        return UNKNOWN_FILTER_COEFFICIENT
    return UNKNOWN_FILTER_COEFFICIENT


def _range_conjunct(op: str, sym: str, v: float, symbols: dict) -> float:
    st = symbols.get(sym, _UNKNOWN)
    lo, hi, ndv = st.lo, st.hi, st.ndv
    nonnull = 1.0 - st.null_frac
    if op == "eq":
        symbols[sym] = replace(st, lo=v, hi=v, ndv=1.0, null_frac=0.0)
        return (1.0 / ndv) * nonnull if ndv else 0.1
    if op == "ne":
        if ndv:
            return (1.0 - 1.0 / ndv) * nonnull
        return 0.9
    if lo is None or hi is None or hi <= lo:
        # unknown or single-valued domain
        sel = UNKNOWN_FILTER_COEFFICIENT
        if lo is not None and hi is not None and hi == lo:
            holds = {
                "lt": lo < v, "le": lo <= v, "gt": lo > v, "ge": lo >= v,
            }[op]
            sel = nonnull if holds else 0.0
        return sel
    width = hi - lo
    if op in ("lt", "le"):
        frac = (v - lo) / width
        new_hi = min(hi, v)
        symbols[sym] = replace(
            st, hi=new_hi,
            ndv=ndv * min(max(frac, 0.0), 1.0) if ndv else None,
            null_frac=0.0,
        )
    else:
        frac = (hi - v) / width
        new_lo = max(lo, v)
        symbols[sym] = replace(
            st, lo=new_lo,
            ndv=ndv * min(max(frac, 0.0), 1.0) if ndv else None,
            null_frac=0.0,
        )
    return min(max(frac, 0.0), 1.0) * nonnull


def _expr_stats(e: RowExpression, src: PlanStats) -> SymbolStats:
    """Derived-expression stats: conservative (no exact bounds except
    trivially safe forms)."""
    if isinstance(e, Cast):
        inner = _expr_stats(e.arg, src)
        if _same_domain(e.type, e.arg.type):
            return inner
        return replace(inner, lo=None, hi=None, exact=False)
    if isinstance(e, InputRef):
        return src.sym(e.name)
    if isinstance(e, Literal):
        v = _literal_num(e)
        if v is None:
            return SymbolStats(ndv=1.0)
        return SymbolStats(ndv=1.0, lo=v, hi=v, exact=True)
    return _UNKNOWN


# ---- aggregates / joins ----------------------------------------------------

def _aggregate_stats(node: P.Aggregate, md, cache) -> PlanStats:
    src = estimate(node.source, md, cache)
    if not node.group_keys:
        return PlanStats(1.0, {
            sym: SymbolStats(ndv=1.0) for sym in node.aggregates
        })
    groups = 1.0
    known = False
    for k in node.group_keys:
        ndv = src.sym(k).ndv
        if ndv:
            groups *= max(ndv, 1.0)
            known = True
    if not known:
        groups = max(src.rows / 10.0, 1.0)
    rows = min(groups, src.rows)
    symbols = {k: src.sym(k) for k in node.group_keys}
    for sym, call in node.aggregates.items():
        if call.name in ("count", "count_all", "count_if", "count_final"):
            symbols[sym] = SymbolStats(lo=0.0, null_frac=0.0)
        else:
            symbols[sym] = _UNKNOWN
    return PlanStats(rows, symbols)


def _join_stats(node: P.Join, md, cache) -> PlanStats:
    l = estimate(node.left, md, cache)
    r = estimate(node.right, md, cache)
    symbols = {**l.symbols, **r.symbols}
    if node.kind == "cross" or not node.criteria:
        rows = l.rows * r.rows
    else:
        rows = l.rows * r.rows
        for a, b in node.criteria:
            na, nb = l.sym(a).ndv, r.sym(b).ndv
            denom = max(na or 0.0, nb or 0.0)
            if denom <= 0:
                denom = max(min(l.rows, r.rows), 1.0)
            rows /= denom
            if node.kind == "inner":
                # only an inner join guarantees surviving rows matched
                # BOTH sides; outer joins keep unmatched rows whose
                # keys lie outside the other side's range (and may be
                # NULL-extended), so intersected exact bounds would
                # corrupt value-range key packing
                joined = _intersect_sym(l.sym(a), r.sym(b))
                symbols[a] = joined
                symbols[b] = joined
        rows = max(rows, 1.0)
    if node.kind == "left":
        rows = max(rows, l.rows)
    elif node.kind == "right":
        rows = max(rows, r.rows)
    elif node.kind == "full":
        rows = max(rows, l.rows + r.rows)
    if node.filter is not None:
        rows *= UNKNOWN_FILTER_COEFFICIENT
    return PlanStats(max(rows, 1.0), symbols)


def _intersect_sym(a: SymbolStats, b: SymbolStats) -> SymbolStats:
    ndv = None
    if a.ndv is not None and b.ndv is not None:
        ndv = min(a.ndv, b.ndv)
    a_full = a.lo is not None and a.hi is not None
    b_full = b.lo is not None and b.hi is not None
    lo = hi = None
    if a_full and b_full:
        lo, hi = max(a.lo, b.lo), min(a.hi, b.hi)
    elif a_full:
        lo, hi = a.lo, a.hi
    elif b_full:
        lo, hi = b.lo, b.hi
    return SymbolStats(
        ndv=ndv, lo=lo, hi=hi, null_frac=0.0,
        # the joined column only keeps rows from BOTH inputs, so either
        # side's exact bounds alone still bound it
        exact=a.exact or b.exact,
    )


# ---- plan annotation -------------------------------------------------------

#: varchar columns beyond this NDV scan hash-coded when eligible
#: (session ``varchar_hash_ndv`` overrides; the sorted-dictionary
#: build is an O(n log n) host string sort — the SF1 l_comment cliff)
VARCHAR_HASH_NDV = 1 << 20


def _hash_varchar_candidates(plan: P.PlanNode, metadata, threshold):
    """Scan symbols eligible for hash-coded varchar: used ONLY as group
    keys, plain join criteria (both sides eligible), count/distinct
    arguments, or raw output — never in ordering, range/LIKE
    predicates, projections or other expressions (those need sorted
    dictionary codes)."""
    from trino_tpu.expr.ir import InputRef as Ref

    scans: dict[str, tuple[P.TableScan, str]] = {}
    unsafe: set[str] = set()
    join_edges: list[tuple[str, str]] = []
    #: identity-projection renames (out symbol -> source symbol):
    #: unsafety flows back through them to the scan symbol
    aliases: list[tuple[str, str]] = []

    def expr_refs(e):
        out = set()

        def w(x):
            if isinstance(x, Ref):
                out.add(x.name)
            for a in getattr(x, "args", ()):
                w(a)
            arg = getattr(x, "arg", None)
            if arg is not None:
                w(arg)

        if e is not None:
            w(e)
        return out

    def walk(node):
        for s in node.sources:
            walk(s)
        if isinstance(node, P.TableScan):
            for sym, col in node.assignments.items():
                if isinstance(node.outputs.get(sym), T.VarcharType):
                    scans[sym] = (node, col)
            return
        if isinstance(node, P.Filter):
            unsafe.update(expr_refs(node.predicate))
        elif isinstance(node, P.Project):
            for out_sym, e in node.assignments.items():
                if isinstance(e, Ref):
                    aliases.append((out_sym, e.name))
                else:
                    unsafe.update(expr_refs(e))
        elif isinstance(node, P.Aggregate):
            for call in node.aggregates.values():
                names = set()
                for a in call.args:
                    names |= expr_refs(a)
                names |= expr_refs(call.filter)
                if call.name not in ("count", "count_all"):
                    unsafe.update(names)
                elif not all(isinstance(a, Ref) for a in call.args):
                    unsafe.update(names)
        elif isinstance(node, P.Join):
            join_edges.extend(node.criteria)
            unsafe.update(expr_refs(node.filter))
        elif isinstance(node, P.SemiJoin):
            join_edges.extend(node.keys)
            unsafe.update(expr_refs(node.filter))
        elif isinstance(node, (P.Sort, P.TopN)):
            unsafe.update(k.symbol for k in node.keys)
        elif isinstance(node, P.Window):
            unsafe.update(k.symbol for k in node.order_keys)
            # partition keys are equality-style, but the window
            # executor has no hash-lane handling yet
            unsafe.update(node.partition_by)
            for call in node.functions.values():
                for a in call.args:
                    unsafe.update(expr_refs(a))
        elif isinstance(node, P.Unnest):
            for a in node.arrays:
                for e in (a if isinstance(a, tuple) else (a,)):
                    unsafe.update(expr_refs(e))
        elif isinstance(node, P.Union):
            for ins in node.symbol_map.values():
                unsafe.update(ins)  # branch remaps need dictionaries

    walk(plan)
    # unsafety propagates backwards through identity renames to the
    # scan symbol (ORDER BY on an alias is an ordered use of the base)
    changed = True
    while changed:
        changed = False
        for out_sym, in_sym in aliases:
            if out_sym in unsafe and in_sym not in unsafe:
                unsafe.add(in_sym)
                changed = True

    def eligible(sym):
        if sym in unsafe or sym not in scans:
            return False
        node, col = scans[sym]
        try:
            cs = metadata.connector(node.catalog).column_stats(
                node.schema, node.table, col
            )
        except Exception:
            return False
        return cs is not None and cs.ndv is not None and cs.ndv > threshold

    # join-connected symbols hash together or not at all (a mixed
    # hash/dictionary join would need cross-encoding remaps); an edge
    # touching any symbol we cannot prove hash-eligible (including
    # renamed/derived ones) disqualifies its partner too
    chosen = {s for s in scans if eligible(s)}
    # resolve projection renames back to base symbols so an aliased
    # join edge still couples (or disqualifies) its endpoints
    alias_to_base = {}
    for out_sym, in_sym in aliases:
        alias_to_base[out_sym] = in_sym

    def base_of(sym):
        seen = set()
        while sym in alias_to_base and sym not in seen:
            seen.add(sym)
            sym = alias_to_base[sym]
        return sym

    changed = True
    while changed:
        changed = False
        for a0, b0 in join_edges:
            a, b = base_of(a0), base_of(b0)
            if a not in scans and b not in scans:
                continue
            if not (a in chosen and b in chosen):
                for s in (a, b):
                    if s in chosen:
                        chosen.discard(s)
                        changed = True
    for sym in chosen:
        node, _ = scans[sym]
        node.hash_varchar = sorted(
            set(node.hash_varchar or []) | {sym}
        )


def annotate(
    plan: P.PlanNode, metadata: Metadata, session=None
) -> P.PlanNode:
    """Annotate the final plan with executor-facing statistics:

    - ``Aggregate.est_groups``: expected distinct group count — sizes
      the group table upfront so capacity-overflow retries become rare
      (the reference reserves FlatHash capacity from stats the same
      way).
    - ``Aggregate.key_ranges``: {symbol: (lo, hi)} EXACT integer value
      bounds for group keys — the executor packs keys into
      bit_length(hi-lo) bits, turning multi-pass lexsorts into single
      u64 sort passes (value-range key packing, BASELINE.md).

    Mutates nodes in place (annotation fields only) and returns plan.
    """
    cache: dict = {}

    def walk(node: P.PlanNode):
        for s in node.sources:
            walk(s)
        if isinstance(node, P.Join) and node.criteria and node.kind == "inner":
            l = estimate(node.left, metadata, cache)
            r = estimate(node.right, metadata, cache)
            range_keep = 1.0
            member_keep = 1.0
            known = False
            for a, b in node.criteria:
                sa, sb = l.sym(a), r.sym(b)
                if sa.ndv and sb.ndv:
                    member_keep = min(
                        member_keep, min(1.0, sb.ndv / sa.ndv)
                    )
                    known = True
                if (
                    sa.lo is not None and sa.hi is not None
                    and sb.lo is not None and sb.hi is not None
                    and sa.hi > sa.lo
                ):
                    overlap = max(
                        0.0, min(sa.hi, sb.hi) - max(sa.lo, sb.lo)
                    )
                    range_keep = min(
                        range_keep, overlap / (sa.hi - sa.lo)
                    )
            node.df_range_keep = (
                range_keep if known or range_keep < 1.0 else None
            )
            node.df_keep_frac = member_keep if known else None
        if isinstance(node, P.Aggregate) and node.group_keys:
            src = estimate(node.source, metadata, cache)
            groups = estimate(node, metadata, cache).rows
            node.est_groups = groups
            ranges = {}
            for k in node.group_keys:
                st = src.sym(k)
                if not st.exact or st.lo is None or st.hi is None:
                    continue
                t = node.outputs.get(k)
                if t is None or not _int_domain(t):
                    continue
                # int bounds stay ints through the whole stats chain;
                # a float here means something lossy touched them —
                # never pack on a possibly-rounded bound
                if not (isinstance(st.lo, int) and isinstance(st.hi, int)):
                    continue
                lo, hi = st.lo, st.hi
                if hi >= lo:
                    ranges[k] = (lo, hi)
            node.key_ranges = ranges or None

    walk(plan)
    threshold = VARCHAR_HASH_NDV
    budgeted = False
    if session is not None:
        threshold = int(
            session.properties.get("varchar_hash_ndv", threshold)
        )
        # streamed (budget-mode) scans chunk per Split and would build
        # chunk-local pools mixing with resident hash columns; hash
        # coding stays off under a budget until the streamed path
        # carries pools
        budgeted = bool(session.properties.get("hbm_budget_bytes"))
    if threshold > 0 and not budgeted:
        _hash_varchar_candidates(plan, metadata, threshold)
    return plan


def _int_domain(t: T.DataType) -> bool:
    """Types whose storage is an integer domain where (value - lo) is
    meaningful and bounded: ints, dates, timestamps, decimals. Varchar
    uses dictionary codes (handled separately); floats excluded (bit
    patterns are not contiguous)."""
    import numpy as np

    if isinstance(t, T.VarcharType) or isinstance(t, T.BooleanType):
        return False
    return np.dtype(t.np_dtype).kind == "i"
