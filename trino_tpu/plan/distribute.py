"""Distribution planning: turn a single-node logical plan into a
mesh-distributed plan.

The analog of the reference's exchange placement + fragmentation
(AddExchanges, MAIN/sql/planner/optimizations/AddExchanges.java:142;
PlanFragmenter, MAIN/sql/planner/PlanFragmenter.java:91), collapsed
into one bottom-up pass suited to a batch-synchronous SPMD engine:

- every node is assigned a distribution property: ``dist`` (rows
  sharded over the mesh axis) or ``single`` (one ordinary device page);
- grouped aggregations over distributed inputs split into a shard-local
  PARTIAL step, a hash ``Exchange`` on the group keys (one all_to_all
  on ICI), and a FINAL combine step — the reference's
  partial/final HashAggregationOperator pair;
- TopN/Limit split into shard-local partials and a gathered final;
- joins get a ``distribution``: BROADCAST (build side replicated to
  every shard — FIXED_BROADCAST_DISTRIBUTION) when the build side is
  estimated small, else PARTITIONED (both sides hash-exchanged on the
  join keys — FIXED_HASH_DISTRIBUTION). Joins repartition *inside* the
  executor so varchar join keys are hashed on unified dictionary codes;
- ``Exchange(single)`` marks the gather boundary; above it the plan
  runs on the ordinary local executor (the coordinator-side final
  stage).
"""

from __future__ import annotations

from dataclasses import replace as dc_replace

from trino_tpu import types as T
from trino_tpu.exec.aggregates import VARIANCE_FNS
from trino_tpu.expr.ir import AggCall, Call, Cast, InputRef
from trino_tpu.metadata import Metadata
from trino_tpu.plan import nodes as P
from trino_tpu.metadata import Session
from trino_tpu.plan import stats as S

__all__ = ["add_exchanges", "fragment_saltable"]

#: builds beyond this many rows never broadcast regardless of the cost
#: model — each shard must hold a full replica in HBM (session
#: property ``broadcast_join_row_limit`` overrides)
DEFAULT_BROADCAST_ROW_LIMIT = 2_000_000

#: aggregate functions whose partial state combines with the same
#: function (min of mins, etc.)
_SELF_COMBINING = {
    "min", "max", "any_value", "arbitrary", "bool_and", "bool_or",
}


def fragment_saltable(root: P.PlanNode) -> tuple[bool, str]:
    """Whether a stage fragment may legally run SALTED — i.e. with one
    hot input partition split row-wise across salt tasks (the other
    aligned inputs replicated to every salt) and the sub-results simply
    unioned by the downstream exchange.

    A row split of one input distributes over filters, projections,
    inner joins (the replicated side sees every row), and PARTIAL
    aggregates (partials merge in the consumer's FINAL step) — exactly
    the operator set ``add_exchanges`` leaves inside a partitioned-join
    fragment. It does NOT distribute over outer/semi joins (preserved
    or marked rows would duplicate across salts), FINAL/SINGLE
    aggregates, window functions, or order/count-sensitive operators.
    Returns ``(ok, reason)`` with ``reason`` naming the first blocking
    operator."""
    verdict: list = [True, ""]
    seen: set[int] = set()

    def flag(msg: str) -> None:
        if verdict[0]:
            verdict[0], verdict[1] = False, msg

    def walk(n: P.PlanNode) -> None:
        if id(n) in seen:
            return
        seen.add(id(n))
        if isinstance(n, P.Join):
            if n.kind != "inner":
                flag(
                    f"{n.kind} join does not distribute over a row "
                    f"split of one input"
                )
        elif isinstance(n, P.Aggregate):
            if n.step != "PARTIAL":
                flag(
                    f"{n.step} aggregate does not merge across salted "
                    f"sub-partitions"
                )
        elif isinstance(n, (P.Sort, P.TopN)):
            flag("order-sensitive operator above the salted exchange")
        elif isinstance(n, P.Limit):
            flag("count-sensitive Limit above the salted exchange")
        elif isinstance(n, P.Window):
            flag("window functions require whole partitions")
        elif isinstance(n, P.SemiJoin):
            flag("semi-join marks do not merge across salted "
                 "sub-partitions")
        for s in n.sources:
            walk(s)

    walk(root)
    return bool(verdict[0]), str(verdict[1])


class _Ctx:
    """Distribution-planning context: metadata + mesh shape + session
    knobs + a shared stats cache."""

    def __init__(self, metadata: Metadata, n_shards: int, session):
        self.md = metadata
        self.n_shards = max(int(n_shards), 2)
        from trino_tpu import session_properties as SP

        self.mode = str(
            SP.get(session, "join_distribution_type")
        ).upper()
        self.broadcast_limit = float(
            SP.get(session, "broadcast_join_row_limit")
        )
        self.stats_cache: dict = {}
        #: writer stages may fan out (hash / round-robin) only when the
        #: executor can run non-single exchanges host-side (fleet); a
        #: real device mesh gathers below the writer instead
        self.scaled_writers = False

    def rows(self, node: P.PlanNode) -> float:
        return S.estimate(node, self.md, self.stats_cache).rows

    def should_broadcast(self, probe: P.PlanNode, build: P.PlanNode) -> bool:
        """DetermineJoinDistributionType analog, with the exchange cost
        model of the collective fabric: PARTITIONED all_to_alls both
        sides once (cost ~ probe + build rows); BROADCAST all_gathers
        the build to every shard (cost ~ build * n_shards) and leaves
        the probe in place. Broadcast also removes a probe-side
        repartition ahead of downstream aggregations, so ties favor
        it."""
        if self.mode == "BROADCAST":
            return True
        if self.mode == "PARTITIONED":
            return False
        build_rows = self.rows(build)
        if build_rows > self.broadcast_limit:
            return False
        # an out-of-core storage probe must not be repartitioned: a
        # hash exchange materializes the WHOLE table through the spool
        # — exactly what streamed split-granular scans exist to avoid.
        # Replicate the (row-limit-bounded) build and leave the fact
        # table streaming in place.
        if self._streams_storage(probe):
            return True
        probe_rows = self.rows(probe)
        return build_rows * self.n_shards <= probe_rows + build_rows

    def _streams_storage(self, node: P.PlanNode) -> bool:
        """True when the subtree is a Filter/Project chain over a scan
        of a streamable storage connector (parquet)."""
        while isinstance(node, (P.Filter, P.Project)):
            node = node.source
        if not isinstance(node, P.TableScan):
            return False
        try:
            conn = self.md.connector(node.catalog)
        except KeyError:
            return False
        return bool(getattr(conn, "streamable", False))


def add_exchanges(
    plan: P.PlanNode,
    metadata: Metadata,
    n_shards: int = 8,
    session: Session | None = None,
    scaled_writers: bool = False,
) -> P.PlanNode:
    ctx = _Ctx(metadata, n_shards, session)
    ctx.scaled_writers = bool(scaled_writers)
    node, _ = _walk(plan, ctx)
    return node


def _gather(node: P.PlanNode) -> P.PlanNode:
    return P.Exchange(
        dict(node.outputs), source=node, partitioning="single",
    )


def _walk(node: P.PlanNode, ctx: _Ctx) -> tuple[P.PlanNode, str]:
    """Returns (rewritten node, distribution in {'dist', 'single'})."""
    if isinstance(node, P.TableScan):
        return node, "dist"
    if isinstance(node, P.Values):
        return node, "single"

    if isinstance(node, (P.Filter, P.Project, P.GroupId)):
        # GroupId is row-parallel: each shard replicates its own rows
        # per set; the aggregation above exchanges on (id, keys)
        src, d = _walk(node.source, ctx)
        return dc_replace(node, source=src), d

    if isinstance(node, P.Output):
        src, d = _walk(node.source, ctx)
        if d == "dist":
            src = _gather(src)
        return dc_replace(node, source=src), "single"

    if isinstance(node, P.Sort):
        src, d = _walk(node.source, ctx)
        if d == "dist":
            # distributed sort: range-partition on the first key
            # (sampled splitters), sort per shard, ordered gather —
            # the sort WORK distributes; only the ordered result
            # concatenates (MergeOperator/MergeSortedPages analog,
            # replacing the gather-raw-rows-then-sort plan)
            rng = P.Exchange(
                dict(src.outputs), source=src, partitioning="range",
                sort_keys=list(node.keys),
            )
            local = dc_replace(node, source=rng)
            return P.Exchange(
                dict(node.outputs), source=local, partitioning="single",
                ordered=True,
            ), "single"
        return dc_replace(node, source=src), "single"

    if isinstance(node, P.TopN):
        src, d = _walk(node.source, ctx)
        if d == "dist":
            partial = dc_replace(node, source=src)
            return dc_replace(node, source=_gather(partial)), "single"
        return dc_replace(node, source=src), "single"

    if isinstance(node, P.Limit):
        src, d = _walk(node.source, ctx)
        if d == "dist":
            partial = P.Limit(
                dict(node.outputs), source=src,
                count=node.count + node.offset if node.count >= 0 else -1,
                offset=0,
            )
            return dc_replace(node, source=_gather(partial)), "single"
        return dc_replace(node, source=src), "single"

    if isinstance(node, P.Aggregate):
        return _walk_aggregate(node, ctx)

    if isinstance(node, P.Join):
        return _walk_join(node, ctx)

    if isinstance(node, P.SemiJoin):
        src, sd = _walk(node.source, ctx)
        filt, fd = _walk(node.filter_source, ctx)
        if sd == "single":
            if fd == "dist":
                filt = _gather(filt)
            return dc_replace(node, source=src, filter_source=filt), "single"
        # source sharded; replicate the filter side to every shard
        bcast = P.Exchange(
            dict(filt.outputs), source=filt, partitioning="broadcast",
            input_dist=fd,
        )
        return dc_replace(node, source=src, filter_source=bcast), "dist"

    if isinstance(node, P.TableWriter):
        # TableWriterNode placement (MAIN/sql/planner/
        # AddExchanges.java visitTableWriter analog): with scaled
        # writers, partitioned targets hash-exchange on the partition
        # columns so each writer owns whole partitions (one file set
        # per partition per writer); unpartitioned targets round-robin
        # across task_writer_count writers. On a real device mesh the
        # writer runs host-side, so gather the child and write single.
        src, d = _walk(node.source, ctx)
        if d == "dist" and ctx.scaled_writers:
            pb = [str(k) for k in node.handle.get("partition_by") or []]
            if pb:
                ts_cols = [c for c, _ in node.handle["columns"]]
                pos = {c: i for i, c in enumerate(ts_cols)}
                hash_syms = [node.columns[pos[k]] for k in pb]
                ex = P.Exchange(
                    dict(src.outputs), source=src,
                    partitioning="hash", hash_symbols=hash_syms,
                )
            else:
                ex = P.Exchange(
                    dict(src.outputs), source=src,
                    partitioning="round_robin",
                )
            return dc_replace(node, source=ex), "dist"
        if d == "dist":
            src = _gather(src)
        return dc_replace(node, source=src), "single"

    if isinstance(node, P.TableFinish):
        # single coordinator-side commit task over the gathered
        # fragment stream
        src, d = _walk(node.source, ctx)
        if d == "dist":
            src = _gather(src)
        return dc_replace(node, source=src), "single"

    # unknown nodes: force single execution of every source
    srcs = []
    for s in node.sources:
        s2, d = _walk(s, ctx)
        srcs.append(_gather(s2) if d == "dist" else s2)
    if srcs:
        from trino_tpu.plan.optimizer import _replace_sources

        node = _replace_sources(node, srcs)
    return node, "single"


# ---- joins -----------------------------------------------------------------

def _flip(node: P.Join) -> P.Join:
    return dc_replace(
        node, left=node.right, right=node.left,
        criteria=[(b, a) for a, b in node.criteria],
    )


def _walk_join(node: P.Join, ctx: _Ctx) -> tuple[P.PlanNode, str]:
    left, ld = _walk(node.left, ctx)
    right, rd = _walk(node.right, ctx)

    if ld == "single" and rd == "single":
        return dc_replace(node, left=left, right=right), "single"

    if node.kind == "cross":
        if ld == "single":
            # keep the sharded side streaming; replicate the single one
            # by flipping (cross join output columns come from
            # node.outputs, so side order is cosmetic)
            node, left, ld, right, rd = _flip(node), right, rd, left, ld
        bcast = P.Exchange(
            dict(right.outputs), source=right, partitioning="broadcast",
            input_dist=rd,
        )
        return dc_replace(
            node, left=left, right=bcast, distribution="BROADCAST"
        ), "dist"

    if node.kind in ("right", "full"):
        # both sides must be co-partitioned: a replicated build side
        # would emit its unmatched rows once per shard
        if ld == "single" or rd == "single":
            if ld == "dist":
                left = _gather(left)
            if rd == "dist":
                right = _gather(right)
            return dc_replace(node, left=left, right=right), "single"
        return dc_replace(
            node, left=left, right=right, distribution="PARTITIONED"
        ), "dist"

    if node.kind == "inner" and ld == "single":
        node, left, ld, right, rd = _flip(node), right, rd, left, ld
    if node.kind == "left" and ld == "single":
        # probe side must stay partitioned-or-single; gather the build
        if rd == "dist":
            right = _gather(right)
        return dc_replace(node, left=left, right=right), "single"

    small_build = rd == "single" or ctx.should_broadcast(left, right)
    if small_build:
        bcast = P.Exchange(
            dict(right.outputs), source=right, partitioning="broadcast",
            input_dist=rd,
        )
        return dc_replace(
            node, left=left, right=bcast, distribution="BROADCAST"
        ), "dist"
    return dc_replace(
        node, left=left, right=right, distribution="PARTITIONED"
    ), "dist"


def _two_level_distinct(node: P.Aggregate, src: P.PlanNode, dedupe_keys):
    """Skew-proof distinct aggregation: exchange on (group keys +
    distinct column) so a hot GROUP key spreads across shards by its
    distinct values, dedupe the colocated pairs globally, then run the
    remaining plain aggregation as a second partial/final exchange on
    the group keys alone (tiny: one row per group per shard).

    The raw-row route this replaces hashed on the group keys only —
    a 90%-one-key GROUP BY sent 90% of the pairs to one shard and
    escalated the exchange to SkewOverflow (VERDICT r3 weak #3;
    reference: pre-aggregation + MarkDistinct before the exchange).
    Applies when every aggregate is DISTINCT over the same single
    column list; returns None otherwise."""
    plain = {
        sym: AggCall(c.name, c.args, c.type, filter=c.filter)
        for sym, c in node.aggregates.items()
    }
    post = dc_replace(node, aggregates=plain, source=None)
    try:
        partial, final = _split_aggregate(post)
    except NotImplementedError:
        return None
    # shard-local dedupe, pair exchange, global dedupe
    pre = P.Aggregate(
        dict(src.outputs), source=src, group_keys=list(dedupe_keys),
        aggregates={}, step="PARTIAL",
    )
    ex1 = P.Exchange(
        dict(pre.outputs), source=pre, partitioning="hash",
        hash_symbols=list(dedupe_keys),
    )
    dedup = P.Aggregate(
        dict(ex1.outputs), source=ex1, group_keys=list(dedupe_keys),
        aggregates={}, step="PARTIAL",
    )
    partial = dc_replace(partial, source=dedup)
    ex2 = P.Exchange(
        dict(partial.outputs), source=partial, partitioning="hash",
        hash_symbols=list(node.group_keys),
    )
    return dc_replace(final, source=ex2)


# ---- aggregates ------------------------------------------------------------

def _walk_aggregate(node: P.Aggregate, ctx: _Ctx) -> tuple[P.PlanNode, str]:
    src, d = _walk(node.source, ctx)
    if d == "single":
        return dc_replace(node, source=src), "single"

    if any(c.distinct for c in node.aggregates.values()):
        # DISTINCT needs every row of a group on one shard. Instead of
        # exchanging RAW rows (O(data) shuffle), dedupe per shard first
        # when every distinct argument is a plain column: a shard-local
        # group-by over (group keys + distinct args) collapses
        # duplicates, so the exchange carries at most NDV rows per
        # shard (the MarkDistinct-before-exchange analog; VERDICT
        # flagged the raw-row route as a full-data shuffle).
        distinct_syms = []
        simple = True
        for c in node.aggregates.values():
            if not c.distinct:
                continue
            for a in c.args:
                if isinstance(a, InputRef):
                    distinct_syms.append(a.name)
                else:
                    simple = False
        if node.group_keys:
            if simple and distinct_syms:
                dedupe_keys = list(dict.fromkeys(
                    list(node.group_keys) + distinct_syms
                ))
                if set(dedupe_keys) == set(src.outputs) and not any(
                    not c.distinct for c in node.aggregates.values()
                ):
                    # only safe when NO aggregate needs the raw rows
                    # (a non-distinct agg alongside would lose rows)
                    two_level = _two_level_distinct(
                        node, src, dedupe_keys
                    )
                    if two_level is not None:
                        return two_level, "dist"
                    pre = P.Aggregate(
                        dict(src.outputs), source=src,
                        group_keys=dedupe_keys, aggregates={},
                        step="PARTIAL",
                    )
                    src = pre
            ex = P.Exchange(
                dict(src.outputs), source=src, partitioning="hash",
                hash_symbols=list(node.group_keys),
            )
            return dc_replace(node, source=ex), "dist"
        return dc_replace(node, source=_gather(src)), "single"

    try:
        partial, final = _split_aggregate(node)
    except NotImplementedError:
        # aggregates without a partial form (e.g. max_by pairs): route
        # raw rows by group-key hash and aggregate in one step
        if node.group_keys:
            ex = P.Exchange(
                dict(src.outputs), source=src, partitioning="hash",
                hash_symbols=list(node.group_keys),
            )
            return dc_replace(node, source=ex), "dist"
        return dc_replace(node, source=_gather(src)), "single"
    partial = dc_replace(partial, source=src)
    if node.group_keys:
        ex = P.Exchange(
            dict(partial.outputs), source=partial, partitioning="hash",
            hash_symbols=list(node.group_keys),
        )
        return dc_replace(final, source=ex), "dist"
    return dc_replace(final, source=_gather(partial)), "single"


def _split_aggregate(node: P.Aggregate) -> tuple[P.Aggregate, P.Aggregate]:
    """Decompose SINGLE aggregates into PARTIAL states + FINAL combines
    (the reference's partial/intermediate/final accumulator steps,
    MAIN/operator/aggregation/; AddExchanges splits the step the same
    way)."""
    partial_aggs: dict[str, AggCall] = {}
    final_aggs: dict[str, AggCall] = {}
    for sym, call in node.aggregates.items():
        name = call.name
        if name in ("count", "count_all", "count_if"):
            partial_aggs[sym] = call
            final_aggs[sym] = AggCall(
                "count_final", (InputRef(T.BIGINT, sym),), call.type
            )
        elif name == "sum":
            if isinstance(call.type, T.DecimalType) and call.type.is_long:
                # decimal(38): exact limb states travel as two BIGINTs
                # (the Int128 partial-state serialization analog)
                s_hi, s_lo = f"{sym}$hi", f"{sym}$lo"
                partial_aggs[s_hi] = AggCall(
                    "sum_hi32", call.args, T.BIGINT, filter=call.filter
                )
                partial_aggs[s_lo] = AggCall(
                    "sum_lo32", call.args, T.BIGINT, filter=call.filter
                )
                final_aggs[sym] = AggCall(
                    "decimal_sum_final",
                    (InputRef(T.BIGINT, s_hi), InputRef(T.BIGINT, s_lo)),
                    call.type,
                )
            else:
                partial_aggs[sym] = call
                final_aggs[sym] = AggCall(
                    "sum", (InputRef(call.type, sym),), call.type
                )
        elif name in _SELF_COMBINING:
            partial_aggs[sym] = call
            final_aggs[sym] = AggCall(
                name, (InputRef(call.type, sym),), call.type
            )
        elif name == "avg":
            if isinstance(call.type, T.DecimalType):
                # exact limb states: a plain int64 partial sum would
                # silently wrap past 2^63 (the local path is limb-exact,
                # the distributed/chunked path must match)
                s_hi, s_lo, s_cnt = f"{sym}$hi", f"{sym}$lo", f"{sym}$cnt"
                partial_aggs[s_hi] = AggCall(
                    "sum_hi32", call.args, T.BIGINT, filter=call.filter
                )
                partial_aggs[s_lo] = AggCall(
                    "sum_lo32", call.args, T.BIGINT, filter=call.filter
                )
                partial_aggs[s_cnt] = AggCall(
                    "count", call.args, T.BIGINT, filter=call.filter
                )
                final_aggs[sym] = AggCall(
                    "decimal_avg_final",
                    (
                        InputRef(T.BIGINT, s_hi),
                        InputRef(T.BIGINT, s_lo),
                        InputRef(T.BIGINT, s_cnt),
                    ),
                    call.type,
                )
            else:
                s_sum, s_cnt = f"{sym}$sum", f"{sym}$cnt"
                partial_aggs[s_sum] = AggCall(
                    "sum", call.args, T.DOUBLE, filter=call.filter
                )
                partial_aggs[s_cnt] = AggCall(
                    "count", call.args, T.BIGINT, filter=call.filter
                )
                final_aggs[sym] = AggCall(
                    "avg_final",
                    (InputRef(T.DOUBLE, s_sum), InputRef(T.BIGINT, s_cnt)),
                    call.type,
                )
        elif name in VARIANCE_FNS:
            xd = Cast(T.DOUBLE, call.args[0])
            xx = Call(T.DOUBLE, "multiply", (xd, xd))
            s_n, s_1, s_2 = f"{sym}$n", f"{sym}$s1", f"{sym}$s2"
            partial_aggs[s_n] = AggCall(
                "count", call.args, T.BIGINT, filter=call.filter
            )
            partial_aggs[s_1] = AggCall(
                "sum", (xd,), T.DOUBLE, filter=call.filter
            )
            partial_aggs[s_2] = AggCall(
                "sum", (xx,), T.DOUBLE, filter=call.filter
            )
            final_aggs[sym] = AggCall(
                f"var_final:{name}",
                (
                    InputRef(T.BIGINT, s_n),
                    InputRef(T.DOUBLE, s_1),
                    InputRef(T.DOUBLE, s_2),
                ),
                call.type,
            )
        elif name in ("max_by", "min_by"):
            # partial: per-shard extremal (value, key) pair; FINAL
            # re-runs the same extremal over the pairs (one row per
            # shard per group — no raw-row exchange, so a hot group
            # key cannot skew the shuffle)
            s_v, s_k = f"{sym}$v", f"{sym}$k"
            key_t = call.args[1].type
            partial_aggs[s_v] = call
            partial_aggs[s_k] = AggCall(
                "max" if name == "max_by" else "min",
                (call.args[1],), key_t, filter=call.filter,
            )
            final_aggs[sym] = AggCall(
                name,
                (InputRef(call.type, s_v), InputRef(key_t, s_k)),
                call.type,
            )
        elif name == "approx_distinct":
            # HLL registers as partial state: constant bytes per group
            # through the exchange regardless of NDV (reference:
            # ApproximateCountDistinctAggregations.java)
            from trino_tpu.exec.aggregates import (
                HLL_GLOBAL_BUCKETS,
                HLL_GROUPED_BUCKETS,
            )

            m = HLL_GROUPED_BUCKETS if node.group_keys else HLL_GLOBAL_BUCKETS
            st = T.SketchType("hll", m)
            s_hll = f"{sym}$hll"
            partial_aggs[s_hll] = AggCall(
                "approx_distinct_partial", call.args, st, filter=call.filter
            )
            final_aggs[sym] = AggCall(
                "approx_distinct_final", (InputRef(st, s_hll),), T.BIGINT
            )
        elif name == "approx_percentile":
            # mergeable quantile summary (evenly-spaced order
            # statistics + count) replacing the exact holistic sort
            # when the plan splits (reference: qdigest partial state,
            # ApproximateDoublePercentileAggregations.java)
            from trino_tpu.expr.ir import Literal

            if not isinstance(call.args[1], Literal) or isinstance(
                call.type, T.DecimalType
            ) and call.type.is_long:
                raise NotImplementedError(
                    "approx_percentile split needs a literal percentile"
                )
            from trino_tpu.exec.aggregates import (
                QUANT_GLOBAL_POINTS,
                QUANT_GROUPED_POINTS,
            )

            k = (
                QUANT_GROUPED_POINTS if node.group_keys
                else QUANT_GLOBAL_POINTS
            )
            st = T.SketchType("quant", k + 1)
            s_qs = f"{sym}$qs"
            partial_aggs[s_qs] = AggCall(
                "approx_percentile_partial", call.args, st,
                filter=call.filter,
            )
            final_aggs[sym] = AggCall(
                "approx_percentile_final",
                (InputRef(st, s_qs), call.args[1]),
                call.type,
            )
        else:
            raise NotImplementedError(f"no partial split for {name}")

    key_types = {k: node.outputs[k] for k in node.group_keys}
    partial = P.Aggregate(
        {**key_types, **{s: a.type for s, a in partial_aggs.items()}},
        source=None,
        group_keys=list(node.group_keys),
        aggregates=partial_aggs,
        step="PARTIAL",
    )
    final = P.Aggregate(
        dict(node.outputs),
        source=None,
        group_keys=list(node.group_keys),
        aggregates=final_aggs,
        step="FINAL",
    )
    return partial, final
