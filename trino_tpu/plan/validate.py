"""Plan sanity checking: invariants between optimizer passes.

The analog of the reference's PlanSanityChecker pipeline
(MAIN/sql/planner/sanity/PlanSanityChecker.java: ValidateDependenciesChecker,
NoDuplicatePlanNodeIdsChecker, TypeValidator, ValidateStreamingJoins,
DynamicFiltersChecker) for this engine's ~10-pass rewrite pipeline.
Every checker is a pure function over the plan tree; a violation
raises :class:`PlanSanityError` naming the pass that produced the
broken plan, so "the optimizer silently produced a wrong plan" becomes
a located failure instead of a bench-time mystery.

Gating (session property ``plan_validation``):

- ``OFF``   — never validate.
- ``FINAL`` — validate the final optimized plan, the distributed plan
  after ``add_exchanges``, and the fragmented stage DAG (production
  default: one pass over each finished artifact).
- ``FULL``  — additionally validate after every individual optimizer
  rewrite pass (the test default — tests/conftest.py exports
  ``TRINO_TPU_PLAN_VALIDATION=FULL``).

The runtime half of the exchange-completeness story lives behind the
``check_exchange_coverage`` session property: executors and the fleet
coordinator count rows across each exchange edge and raise
:class:`ExchangeCoverageError` naming the edge that dropped rows (the
debug harness for the mesh×fleet wrong-results canary).
"""

from __future__ import annotations

from trino_tpu import types as T
from trino_tpu.expr.ir import (
    AggCall,
    Call,
    Cast,
    InputRef,
    Literal,
    RowExpression,
    join_key_compatible,
)
from trino_tpu.plan import nodes as P

__all__ = [
    "PlanSanityError",
    "ExchangeCoverageError",
    "validate_plan",
    "validate_stages",
    "check_edge_coverage",
    "level",
]


class PlanSanityError(RuntimeError):
    """A plan invariant does not hold. ``phase`` names the optimizer
    pass (or planning step) whose output broke it; ``check`` names the
    violated invariant."""

    def __init__(self, check: str, phase: str, message: str):
        self.check = check
        self.phase = phase
        super().__init__(
            f"plan sanity violation after pass '{phase}' "
            f"[{check}]: {message}"
        )


class ExchangeCoverageError(RuntimeError):
    """A runtime exchange edge did not conserve rows: the rows that
    came out of its partitions do not sum to the rows that went in.
    ``edge`` names the offending edge (mesh collective or fleet
    stage-to-stage spool/direct edge)."""

    def __init__(self, edge: str, rows_in: int, rows_out: int,
                 detail: str = ""):
        self.edge = edge
        self.rows_in = int(rows_in)
        self.rows_out = int(rows_out)
        super().__init__(
            f"exchange coverage violation on edge {edge}: "
            f"{rows_in} rows in, {rows_out} rows out "
            f"(dropped {rows_in - rows_out})"
            + (f" — {detail}" if detail else "")
        )


def level(session) -> str:
    """The session's validation level (OFF | FINAL | FULL)."""
    from trino_tpu import session_properties as SP

    return str(SP.get(session, "plan_validation")).upper()


# ---- expression helpers ----------------------------------------------------

def _expr_refs(e, out: set[str]) -> None:
    if isinstance(e, InputRef):
        out.add(e.name)
    elif isinstance(e, Call):
        for a in e.args:
            _expr_refs(a, out)
    elif isinstance(e, Cast):
        _expr_refs(e.arg, out)
    elif isinstance(e, (Literal, type(None))):
        pass
    elif isinstance(e, RowExpression):
        # future expression kinds: be conservative, consume nothing
        pass


def _refs(*exprs) -> set[str]:
    out: set[str] = set()
    for e in exprs:
        _expr_refs(e, out)
    return out


def _agg_refs(agg: AggCall) -> set[str]:
    out = _refs(*agg.args)
    if agg.filter is not None:
        _expr_refs(agg.filter, out)
    return out


# ---- per-node consumption / production semantics ---------------------------

def _consumed(node: P.PlanNode) -> list[tuple[str, set[str]]]:
    """``(source-scope label, symbols the node consumes from it)``
    pairs. Scope label "any" means the union of all sources."""
    if isinstance(node, P.Filter):
        return [("any", _refs(node.predicate))]
    if isinstance(node, P.Project):
        return [("any", _refs(*node.assignments.values()))]
    if isinstance(node, P.Aggregate):
        used = set(node.group_keys)
        for agg in node.aggregates.values():
            used |= _agg_refs(agg)
        return [("any", used)]
    if isinstance(node, P.Join):
        left = {ls for ls, _ in node.criteria}
        right = {rs for _, rs in node.criteria}
        out: list[tuple[str, set[str]]] = [
            ("left", left), ("right", right)
        ]
        if node.filter is not None:
            out.append(("any", _refs(node.filter)))
        return out
    if isinstance(node, P.SemiJoin):
        out = [
            ("left", {ls for ls, _ in node.keys}),
            ("right", {rs for _, rs in node.keys}),
        ]
        if node.filter is not None:
            out.append(("any", _refs(node.filter)))
        return out
    if isinstance(node, (P.Sort, P.TopN)):
        return [("any", {k.symbol for k in node.keys})]
    if isinstance(node, P.Window):
        used = set(node.partition_by)
        used |= {k.symbol for k in node.order_keys}
        for fn in node.functions.values():
            used |= _refs(*fn.args)
        return [("any", used)]
    if isinstance(node, P.Unnest):
        used: set[str] = set()
        for arr in node.arrays:
            # an element is one array expression, or a (expr, ...) tuple
            if isinstance(arr, (tuple, list)):
                used |= _refs(*arr)
            else:
                used |= _refs(arr)
        return [("any", used)]
    if isinstance(node, P.GroupId):
        used = set()
        for gs in node.grouping_sets:
            used |= set(gs)
        return [("any", used)]
    if isinstance(node, P.Union):
        # handled structurally in _check_node (per-source mapping)
        return []
    if isinstance(node, P.Exchange):
        used = set(node.hash_symbols) if node.partitioning == "hash" else set()
        if node.sort_keys:
            used |= {k.symbol for k in node.sort_keys}
        return [("any", used)]
    if isinstance(node, P.Output):
        return [("any", set(node.symbols))]
    if isinstance(node, P.TableWriter):
        return [("any", set(node.columns))]
    return []


#: nodes whose outputs must be a subset of what their sources produce
#: (plus any symbols the node itself introduces)
def _introduced(node: P.PlanNode) -> set[str]:
    if isinstance(node, P.Project):
        return set(node.assignments)
    if isinstance(node, P.Aggregate):
        return set(node.aggregates)
    if isinstance(node, P.SemiJoin):
        return {node.match_symbol}
    if isinstance(node, P.Window):
        return set(node.functions)
    if isinstance(node, P.Unnest):
        return set(node.element_symbols)
    if isinstance(node, P.GroupId):
        return {node.id_symbol}
    if isinstance(node, (P.TableWriter, P.TableFinish)):
        # generator nodes: fragment rows / the commit count are
        # manufactured, not passed through
        return set(node.outputs)
    return set()


# ---- individual checkers ---------------------------------------------------

def _check_acyclic(root: P.PlanNode, fail) -> None:
    """The analog of NoDuplicatePlanNodeIdsChecker, adapted: plans here
    are DAGs — the grouping-sets planner deliberately shares one
    pre-aggregation subtree across Union branches — so sharing is
    legal, but a node reachable from itself would make every recursive
    rewrite diverge. Flag cycles only."""
    on_stack: set[int] = set()
    done: set[int] = set()

    def walk(n: P.PlanNode) -> None:
        if id(n) in done:
            return
        if id(n) in on_stack:
            fail(
                "acyclic",
                f"{type(n).__name__} node is reachable from itself "
                f"(cycle in the plan graph)",
            )
            return
        on_stack.add(id(n))
        for s in n.sources:
            walk(s)
        on_stack.discard(id(n))
        done.add(id(n))

    walk(root)


def _check_node(node: P.PlanNode, fail) -> None:
    """Symbol resolution + type consistency for one node against its
    immediate sources (ValidateDependenciesChecker + TypeValidator)."""
    srcs = node.sources
    name = type(node).__name__
    avail: dict[str, T.DataType] = {}
    for s in srcs:
        avail.update(s.outputs)

    # leaves produce from thin air; nothing to resolve
    if not srcs:
        if isinstance(node, P.TableScan):
            missing = set(node.outputs) - set(node.assignments)
            if missing:
                fail(
                    "symbols",
                    f"TableScan {node.table}: output symbols "
                    f"{sorted(missing)} have no column assignment",
                )
        return

    # every consumed symbol is produced by the right source(s)
    for scope, used in _consumed(node):
        if scope == "left":
            have = set(srcs[0].outputs)
        elif scope == "right":
            have = set(srcs[1].outputs)
        else:
            have = set(avail)
        missing = used - have
        if missing:
            fail(
                "symbols",
                f"{name} consumes {sorted(missing)} not produced by its "
                f"{scope if scope != 'any' else ''} source(s) "
                f"(available: {sorted(have)})",
            )

    # Union wires outputs per source explicitly
    if isinstance(node, P.Union):
        for sym, per_src in node.symbol_map.items():
            if len(per_src) != len(node.all_sources):
                fail(
                    "symbols",
                    f"Union symbol {sym!r} maps {len(per_src)} inputs "
                    f"for {len(node.all_sources)} sources",
                )
                continue
            for i, (s, isym) in enumerate(zip(node.all_sources, per_src)):
                if isym not in s.outputs:
                    fail(
                        "symbols",
                        f"Union symbol {sym!r} reads {isym!r} absent "
                        f"from source #{i} outputs",
                    )
        extra = set(node.outputs) - set(node.symbol_map)
        if extra:
            fail(
                "symbols",
                f"Union outputs {sorted(extra)} have no symbol mapping",
            )

    # output closure: pass-through outputs must come from some source
    # (or be introduced by the node itself)
    if not isinstance(node, (P.Union, P.Unnest)):
        passthrough = set(node.outputs) - _introduced(node)
        if isinstance(node, P.Aggregate):
            # group keys are the only pass-through an Aggregate has
            stray = passthrough - set(node.group_keys)
            if stray:
                fail(
                    "symbols",
                    f"Aggregate outputs {sorted(stray)} are neither "
                    f"group keys nor aggregate results",
                )
            passthrough &= set(node.group_keys)
        unknown = passthrough - set(avail)
        if unknown:
            fail(
                "symbols",
                f"{name} outputs {sorted(unknown)} that no source "
                f"produces",
            )

    # type consistency: pass-through symbols keep their source type,
    # computed symbols carry their expression's type
    for sym, t in node.outputs.items():
        if isinstance(node, P.Project) and sym in node.assignments:
            et = node.assignments[sym].type
            if et != t:
                fail(
                    "types",
                    f"Project output {sym!r} declared {t} but its "
                    f"expression has type {et}",
                )
            continue
        if isinstance(node, P.Aggregate) and sym in node.aggregates:
            at = node.aggregates[sym].type
            if at != t:
                fail(
                    "types",
                    f"Aggregate output {sym!r} declared {t} but "
                    f"{node.aggregates[sym].name} produces {at}",
                )
            continue
        if isinstance(node, P.Window) and sym in node.functions:
            wt = node.functions[sym].type
            if wt != t:
                fail(
                    "types",
                    f"Window output {sym!r} declared {t} but "
                    f"{node.functions[sym].name} produces {wt}",
                )
            continue
        if isinstance(node, P.Union):
            continue  # per-source types may legitimately widen
        st = avail.get(sym)
        if st is not None and st != t:
            fail(
                "types",
                f"{name} passes {sym!r} through as {t} but its source "
                f"produces {st}",
            )

    # join key compatibility (raw-bits hashability of criteria pairs)
    if isinstance(node, P.Join):
        lo, ro = srcs[0].outputs, srcs[1].outputs
        for ls, rs in node.criteria:
            lt, rt = lo.get(ls), ro.get(rs)
            if lt is None or rt is None:
                continue  # already reported by the symbol check
            if not join_key_compatible(lt, rt):
                fail(
                    "types",
                    f"Join criteria ({ls!r}, {rs!r}) pair incompatible "
                    f"key types {lt} and {rt}",
                )


def _check_exchanges(root: P.PlanNode, fail) -> None:
    """Exchange completeness at the plan level: hash exchanges
    partition on symbols their input actually carries, range exchanges
    carry their sort keys (the pre-fragmentation half of
    ValidateStreamingJoins/exchange checks)."""

    def walk(n: P.PlanNode) -> None:
        if isinstance(n, P.Exchange):
            if n.partitioning == "hash" and not n.hash_symbols:
                fail(
                    "exchanges",
                    "hash Exchange with no partitioning symbols",
                )
            if n.partitioning == "range" and not n.sort_keys:
                fail(
                    "exchanges",
                    "range Exchange with no sort keys",
                )
        for s in n.sources:
            walk(s)

    walk(root)


def _check_dynamic_filters(root: P.PlanNode, fail) -> None:
    """Dynamic-filter well-formedness: a Join annotated with DF hints
    must still have the equi-criteria (the live build side) those
    hints were derived from — a rewrite that strips criteria but keeps
    the annotation would make executors filter on nothing."""

    def walk(n: P.PlanNode) -> None:
        if isinstance(n, P.Join) and (
            n.df_range_keep is not None or n.df_keep_frac is not None
        ):
            if not n.criteria:
                fail(
                    "dynamic-filters",
                    "Join carries dynamic-filter annotations "
                    "(df_range_keep/df_keep_frac) but has no "
                    "equi-criteria to derive a build-side filter from",
                )
        for s in n.sources:
            walk(s)

    walk(root)


def _check_writers(root: P.PlanNode, fail) -> None:
    """Write-path invariants (the TableWriter half of the reference's
    ValidateDependenciesChecker):

    - ``writer-schema``: the writer's column list matches its handle's
      target-table schema positionally, and the source produces each
      column symbol with exactly the declared type;
    - ``writer-fragments``: fragment rows flow only to TableFinish
      (possibly through Exchanges) — any other consumer would read
      uncommitted write metadata as query data;
    - ``writer-partitioning``: a hash exchange feeding a partitioned
      write partitions on exactly the declared partition-column
      symbols, so co-located rows land in one writer's part file."""
    from trino_tpu import types as TT

    parents: dict[int, list[P.PlanNode]] = {}
    nodes: dict[int, P.PlanNode] = {}
    seen: set[int] = set()

    def walk(n: P.PlanNode) -> None:
        if id(n) in seen:
            return
        seen.add(id(n))
        nodes[id(n)] = n
        for s in n.sources:
            parents.setdefault(id(s), []).append(n)
            walk(s)

    walk(root)

    for n in nodes.values():
        if isinstance(n, P.TableWriter):
            h = n.handle
            hcols = list(h.get("columns") or [])
            if len(n.columns) != len(hcols):
                fail(
                    "writer-schema",
                    f"TableWriter for {h.get('schema')}.{h.get('table')}"
                    f" feeds {len(n.columns)} columns into a "
                    f"{len(hcols)}-column target",
                )
            else:
                src_out = n.source.outputs
                for sym, (cname, tstr) in zip(n.columns, hcols):
                    want = TT.type_from_name(tstr)
                    got = src_out.get(sym)
                    if got is None:
                        continue  # symbol closure already reported
                    if got != want:
                        fail(
                            "writer-schema",
                            f"TableWriter column {cname!r} declared "
                            f"{want} in the target table but source "
                            f"symbol {sym!r} produces {got}",
                        )
            # fragments reach TableFinish and nothing else
            cur = n
            while True:
                ps = parents.get(id(cur), [])
                if not ps and cur is root:
                    # fragment root: the consumer is the parent
                    # stage's TableFinish (via RemoteSource) — its
                    # stage re-validates the TableFinish half below
                    break
                if len(ps) != 1:
                    fail(
                        "writer-fragments",
                        f"TableWriter fragments have {len(ps)} "
                        f"consumers; exactly one TableFinish expected",
                    )
                    break
                parent = ps[0]
                if isinstance(parent, P.TableFinish):
                    break
                if not isinstance(parent, P.Exchange):
                    fail(
                        "writer-fragments",
                        f"{type(parent).__name__} consumes TableWriter "
                        f"fragments; only TableFinish (via Exchanges) "
                        f"may read them",
                    )
                    break
                cur = parent
            # partitioned writes hash on the partition columns
            pb = list(h.get("partition_by") or [])
            below = n.source
            if pb and isinstance(below, P.Exchange) and (
                below.partitioning == "hash"
            ):
                pos = {c: i for i, (c, _t) in enumerate(hcols)}
                want_syms = [
                    n.columns[pos[k]] for k in pb
                    if k in pos and pos[k] < len(n.columns)
                ]
                if list(below.hash_symbols) != want_syms:
                    fail(
                        "writer-partitioning",
                        f"partitioned write into {h.get('table')!r} "
                        f"exchanges on {list(below.hash_symbols)} but "
                        f"the declared partition columns {pb} map to "
                        f"{want_syms}",
                    )
        if isinstance(n, P.TableFinish):
            below = n.source
            while isinstance(below, P.Exchange):
                below = below.source
            if not isinstance(below, (P.TableWriter, P.RemoteSource)):
                fail(
                    "writer-fragments",
                    f"TableFinish reads {type(below).__name__}; its "
                    f"input must be TableWriter fragments",
                )


def validate_plan(plan: P.PlanNode, phase: str) -> P.PlanNode:
    """Run every plan-level invariant; raise :class:`PlanSanityError`
    attributing the first violation to ``phase``. Returns the plan so
    call sites can chain."""
    failures: list[tuple[str, str]] = []

    def fail(check: str, message: str) -> None:
        failures.append((check, message))

    _check_acyclic(plan, fail)
    if not failures:
        seen: set[int] = set()

        def walk(n: P.PlanNode) -> None:
            if id(n) in seen:
                return  # shared subtree: check once
            seen.add(id(n))
            _check_node(n, fail)
            for s in n.sources:
                walk(s)

        walk(plan)
        _check_exchanges(plan, fail)
        _check_dynamic_filters(plan, fail)
        _check_writers(plan, fail)
    if failures:
        check, message = failures[0]
        if len(failures) > 1:
            message += f" (+{len(failures) - 1} more violations)"
        raise PlanSanityError(check, phase, message)
    return plan


# ---- fragment / stage-DAG invariants ---------------------------------------

def validate_stages(stages, phase: str = "fragment_plan"):
    """Fragment closure over a ``fragment_plan`` result: every
    RemoteSource resolves to exactly one producing stage, stage inputs
    match the RemoteSources actually present in the fragment, the
    stage DAG is acyclic with children ordered before parents, and
    every aligned (hash) edge partitions on symbols the producer
    fragment actually outputs."""
    failures: list[tuple[str, str]] = []

    def fail(check: str, message: str) -> None:
        failures.append((check, message))

    by_id = {s.stage_id: s for s in stages}
    if len(by_id) != len(stages):
        fail("fragments", "duplicate stage ids in fragment list")
    producer_of = {f"rs{s.stage_id}": s for s in stages}

    for stage in stages:
        # RemoteSources present in the fragment tree (plans are DAGs:
        # the same node object reachable twice is one read, but two
        # distinct RemoteSource objects with one source_id is a
        # fragmentation bug)
        remotes: dict[str, P.RemoteSource] = {}
        walked: set[int] = set()

        def walk(n: P.PlanNode) -> None:
            if id(n) in walked:
                return
            walked.add(id(n))
            if isinstance(n, P.RemoteSource):
                if n.source_id in remotes:
                    fail(
                        "fragments",
                        f"stage {stage.stage_id}: RemoteSource "
                        f"{n.source_id!r} appears twice in one fragment",
                    )
                remotes[n.source_id] = n
            for s in n.sources:
                walk(s)

        walk(stage.root)
        declared = {i.source_id: i for i in stage.inputs}
        if set(remotes) != set(declared):
            fail(
                "fragments",
                f"stage {stage.stage_id}: fragment reads "
                f"{sorted(remotes)} but declares inputs "
                f"{sorted(declared)}",
            )
        for sid, rs in remotes.items():
            producer = producer_of.get(sid)
            if producer is None:
                fail(
                    "fragments",
                    f"stage {stage.stage_id}: RemoteSource {sid!r} has "
                    f"no producing fragment",
                )
                continue
            inp = declared.get(sid)
            if inp is not None and inp.stage_id != producer.stage_id:
                fail(
                    "fragments",
                    f"stage {stage.stage_id}: input {sid!r} declares "
                    f"producer {inp.stage_id!r} but the id resolves to "
                    f"stage {producer.stage_id!r}",
                )
            # the edge's schema: the consumer reads exactly what the
            # producer fragment outputs
            missing = set(rs.outputs) - set(producer.root.outputs)
            if missing:
                fail(
                    "fragments",
                    f"edge {producer.stage_id}->{stage.stage_id}: "
                    f"consumer expects {sorted(missing)} the producer "
                    f"fragment does not output",
                )
            for sym, t in rs.outputs.items():
                pt = producer.root.outputs.get(sym)
                if pt is not None and pt != t:
                    fail(
                        "types",
                        f"edge {producer.stage_id}->{stage.stage_id}: "
                        f"{sym!r} typed {t} on the consumer, {pt} on "
                        f"the producer",
                    )
            # exchange completeness on the wire: a hash edge
            # partitions on symbols the producer actually outputs
            if inp is not None and inp.hash_symbols:
                stray = set(inp.hash_symbols) - set(producer.root.outputs)
                if stray:
                    fail(
                        "exchanges",
                        f"edge {producer.stage_id}->{stage.stage_id}: "
                        f"hash-partitions on {sorted(stray)} absent "
                        f"from the producer outputs",
                    )
            # producer stage partitioning must agree with the edge
            if (
                inp is not None and inp.mode == "aligned"
                and producer.partitioning == "hash"
                and list(producer.hash_symbols) != list(inp.hash_symbols)
            ):
                fail(
                    "exchanges",
                    f"edge {producer.stage_id}->{stage.stage_id}: "
                    f"aligned consumer expects partitioning on "
                    f"{list(inp.hash_symbols)} but the producer "
                    f"partitions on {list(producer.hash_symbols)}",
                )

    # SALTED exchange invariants (coordinator skew mitigation): the
    # salt plan must be structurally sound AND the fragment must
    # distribute over a row split of the salted input, or a broken
    # salted re-plan would return wrong rows silently
    for stage in stages:
        salt = getattr(stage, "salt_plan", None)
        if salt is None:
            continue
        declared = {i.source_id: i for i in stage.inputs}
        src = salt.get("source")
        inp = declared.get(src)
        if inp is None or inp.mode != "aligned":
            fail(
                "salted-exchange",
                f"stage {stage.stage_id}: salted source {src!r} is "
                f"not a declared aligned input",
            )
            continue
        factor = salt.get("factor")
        if not isinstance(factor, int) or factor < 2:
            fail(
                "salted-exchange",
                f"stage {stage.stage_id}: salt count {factor!r} must "
                f"be an integer >= 2 (consistent across the edge)",
            )
        hot = salt.get("hot")
        if (
            not isinstance(hot, list) or not hot
            or any(not isinstance(p, int) or p < 0 for p in hot)
            or len(set(hot)) != len(hot)
        ):
            fail(
                "salted-exchange",
                f"stage {stage.stage_id}: bad hot partition list "
                f"{hot!r}",
            )
        # probe-replication closure: every co-aligned input replicates
        # its hot partitions to all salts, which presumes well-defined
        # hash partitions on the producer side
        for other in stage.inputs:
            if other.source_id == src or other.mode != "aligned":
                continue
            prod = by_id.get(other.stage_id)
            if prod is not None and prod.partitioning != "hash":
                fail(
                    "salted-exchange",
                    f"stage {stage.stage_id}: replicated input "
                    f"{other.source_id!r} comes from a "
                    f"{prod.partitioning}-partitioned producer — "
                    f"probe-replication closure broken",
                )
        from trino_tpu.plan.distribute import fragment_saltable

        ok, reason = fragment_saltable(stage.root)
        if not ok:
            fail(
                "salted-exchange",
                f"stage {stage.stage_id}: fragment is not saltable — "
                f"{reason}",
            )

    # runtime-adaptive partition counts: an override only makes sense
    # on a hash-partitioned stage, and every aligned producer of one
    # consumer must agree on its effective output fan-out (a consumer
    # task reads partition p of ALL its aligned inputs)
    for stage in stages:
        op = int(getattr(stage, "out_partitions", 0) or 0)
        if op < 0:
            fail(
                "adaptive-repartition",
                f"stage {stage.stage_id}: negative output partition "
                f"override {op}",
            )
        if op and stage.partitioning not in ("hash", "round_robin"):
            # hash: runtime-adaptive repartitioning; round_robin: the
            # scaled-writer fan-out (task_writer_count writer tasks)
            fail(
                "adaptive-repartition",
                f"stage {stage.stage_id}: output partition override "
                f"{op} on a {stage.partitioning}-partitioned stage",
            )
    for stage in stages:
        eff = {
            inp.stage_id: int(
                getattr(by_id[inp.stage_id], "out_partitions", 0) or 0
            )
            for inp in stage.inputs
            if inp.mode == "aligned" and inp.stage_id in by_id
        }
        if len(set(eff.values())) > 1:
            fail(
                "adaptive-repartition",
                f"stage {stage.stage_id}: aligned producers disagree "
                f"on output partition count {eff}",
            )

    # acyclicity + topological order (children before parents)
    seen: set[str] = set()
    for stage in stages:
        for inp in stage.inputs:
            if inp.stage_id == stage.stage_id:
                fail(
                    "fragments",
                    f"stage {stage.stage_id} reads its own output "
                    f"(cycle)",
                )
            elif inp.stage_id in by_id and inp.stage_id not in seen:
                fail(
                    "fragments",
                    f"stage {stage.stage_id} reads stage "
                    f"{inp.stage_id} which is not ordered before it "
                    f"(cycle or bad topological order)",
                )
        seen.add(stage.stage_id)

    # each fragment is itself a sane plan
    for stage in stages:
        try:
            validate_plan(stage.root, phase)
        except PlanSanityError as e:
            fail(e.check, f"stage {stage.stage_id}: {e}")
            break

    if failures:
        check, message = failures[0]
        if len(failures) > 1:
            message += f" (+{len(failures) - 1} more violations)"
        raise PlanSanityError(check, phase, message)
    return stages


# ---- runtime exchange-edge coverage (fleet tier) ---------------------------

def check_edge_coverage(stages, task_stats: list[dict]) -> None:
    """Debug assertion behind the ``check_exchange_coverage`` session
    property: for every stage-to-stage exchange edge, the rows
    consumers observed on that edge must sum to the rows the producer
    stage committed. An aligned (hash) edge is read exactly once
    across the consumer stage's partitions; an "all" (gather/
    broadcast) edge is read in full by every consumer task. SALTED
    edges still conserve live rows: the fan-out edge's per-salt row
    slices form a disjoint exact cover (sum == produced), while each
    replicated co-input is priced at produced + (factor-1) x hot
    partition rows. Raises :class:`ExchangeCoverageError` naming the
    first edge that dropped or duplicated rows."""
    by_stage_out: dict[str, int] = {}
    finished: dict[str, list[dict]] = {}
    for row in task_stats:
        if row.get("state") != "FINISHED":
            continue
        sid = row["stage_id"]
        by_stage_out[sid] = by_stage_out.get(sid, 0) + int(
            row.get("rows_out", 0)
        )
        finished.setdefault(sid, []).append(row)

    for stage in stages:
        rows = finished.get(stage.stage_id)
        if rows is None:
            continue
        # only meaningful when every consumer task reported per-edge
        # row counts (older workers / root reads don't)
        if any("edge_rows" not in r for r in rows):
            continue
        salt = getattr(stage, "salt_plan", None)
        for inp in stage.inputs:
            produced = by_stage_out.get(inp.stage_id)
            if produced is None:
                continue
            per_task = [
                int((r.get("edge_rows") or {}).get(inp.source_id, 0))
                for r in rows
            ]
            edge = (
                f"{inp.stage_id}->{stage.stage_id} "
                f"[{inp.mode}"
                + (f" on {list(inp.hash_symbols)}" if inp.hash_symbols
                   else "")
                + "]"
            )
            if inp.mode == "aligned":
                got = sum(per_task)
                expected = produced
                detail = f"per-partition reads {per_task}"
                if salt is not None and inp.source_id != salt["source"]:
                    # replicated-to-salts edge: each hot partition is
                    # read once per salt task instead of once, so the
                    # edge conserves produced + (K-1) x hot rows. (The
                    # fan-out edge conserves exactly: the K salt
                    # slices of a hot partition form a disjoint cover.)
                    prows = finished.get(inp.stage_id) or []
                    if any("partition_rows" not in r for r in prows):
                        continue  # no producer histogram to price it
                    hist: dict[str, int] = {}
                    for r in prows:
                        for p, v in (r.get("partition_rows") or {}).items():
                            hist[str(p)] = hist.get(str(p), 0) + int(v or 0)
                    extra = (int(salt["factor"]) - 1) * sum(
                        hist.get(str(p), 0) for p in salt["hot"]
                    )
                    expected = produced + extra
                    detail += (
                        f" (salted x{salt['factor']}, hot partitions "
                        f"{salt['hot']} replicated: +{extra} rows "
                        f"expected)"
                    )
                if got != expected:
                    raise ExchangeCoverageError(
                        edge, expected, got, detail=detail,
                    )
            else:
                for r, got in zip(rows, per_task):
                    if got != produced:
                        raise ExchangeCoverageError(
                            edge, produced, got,
                            detail=(
                                f"task {r.get('task_id')} read a "
                                f"partial broadcast"
                            ),
                        )
