"""Logical plan nodes.

The analog of the reference's PlanNode hierarchy
(MAIN/sql/planner/plan/, ~60 node types). Symbols are plain strings
with a type map carried per node (the reference's Symbol + TypeProvider
split). Kept deliberately small; nodes are added as engine features
land, mirroring: TableScanNode, FilterNode, ProjectNode,
AggregationNode, JoinNode, SemiJoinNode, SortNode, TopNNode, LimitNode,
OutputNode, ValuesNode, ExchangeNode.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from trino_tpu import types as T
from trino_tpu.expr.ir import AggCall, RowExpression

__all__ = [
    "PlanNode", "TableScan", "Filter", "Project", "Aggregate", "Join",
    "SemiJoin", "Sort", "TopN", "Limit", "Output", "Values", "Exchange",
    "SortKey", "Window", "WindowCall", "Union", "Unnest", "RemoteSource",
    "GroupId", "TableWriter", "TableFinish",
]


@dataclass
class PlanNode:
    #: output symbol name -> type, in column order
    outputs: dict[str, T.DataType]

    @property
    def sources(self) -> list["PlanNode"]:
        return []


@dataclass
class TableScan(PlanNode):
    catalog: str = ""
    schema: str = ""
    table: str = ""
    #: output symbol -> connector column name
    assignments: dict[str, str] = field(default_factory=dict)
    #: symbols to scan as hash-coded varchar (plan.stats.annotate:
    #: high-NDV columns used only in equality/grouping/count contexts —
    #: skips the sorted-dictionary build)
    hash_varchar: list[str] | None = None
    #: optional (start_row, row_count) split assigned to this scan —
    #: the unit of source parallelism in fleet mode (the analog of a
    #: ConnectorSplit riding a task RPC, SPI/connector/ConnectorSplit.java)
    split: tuple[int, int] | None = None
    #: TupleDomain-lite pushdown: connector column name ->
    #: (lo, hi, lo_strict, hi_strict) storage-domain interval derived
    #: from the filter above the scan (plan.optimizer); connectors with
    #: ``supports_domains`` prune storage units by footer stats — the
    #: filter always re-applies, so pruning is advisory-safe
    domains: dict | None = None


@dataclass
class RemoteSource(PlanNode):
    """Leaf standing for the output of an upstream stage, read from the
    spooled exchange (the analog of the reference's RemoteSourceNode,
    MAIN/sql/planner/plan/RemoteSourceNode.java: an ExchangeOperator
    pulling pages produced by another stage's tasks). The executor is
    handed the pages out-of-band (task inputs resolved from spool)."""

    source_id: str = ""


@dataclass
class Filter(PlanNode):
    source: PlanNode = None  # type: ignore[assignment]
    predicate: RowExpression = None  # type: ignore[assignment]

    @property
    def sources(self):
        return [self.source]


@dataclass
class Project(PlanNode):
    source: PlanNode = None  # type: ignore[assignment]
    #: output symbol -> expression over source symbols
    assignments: dict[str, RowExpression] = field(default_factory=dict)

    @property
    def sources(self):
        return [self.source]


@dataclass
class Aggregate(PlanNode):
    source: PlanNode = None  # type: ignore[assignment]
    group_keys: list[str] = field(default_factory=list)
    #: output symbol -> aggregate call (args are symbols of source)
    aggregates: dict[str, AggCall] = field(default_factory=dict)
    #: PARTIAL | FINAL | SINGLE — set by the optimizer when splitting
    step: str = "SINGLE"
    #: stats annotations (plan.stats.annotate): expected distinct group
    #: count, and EXACT (lo, hi) value bounds per integer group key for
    #: value-range key packing
    est_groups: float | None = None
    key_ranges: dict[str, tuple[int, int]] | None = None

    @property
    def sources(self):
        return [self.source]


@dataclass
class Join(PlanNode):
    kind: str = "inner"  # inner/left/right/full/cross
    left: PlanNode = None  # type: ignore[assignment]
    right: PlanNode = None  # type: ignore[assignment]
    #: equi-join clauses: (left symbol, right symbol)
    criteria: list[tuple[str, str]] = field(default_factory=list)
    #: residual non-equi condition evaluated on joined rows
    filter: RowExpression | None = None
    #: join distribution chosen by the optimizer: PARTITIONED|BROADCAST
    distribution: str | None = None
    #: dynamic-filtering hints (plan.stats.annotate): expected probe
    #: keep fraction under a build min/max range filter
    #: (df_range_keep) and under exact build-key membership
    #: (df_keep_frac); None = unknown, executors skip the filter
    df_range_keep: float | None = None
    df_keep_frac: float | None = None

    @property
    def sources(self):
        return [self.left, self.right]


@dataclass
class SemiJoin(PlanNode):
    """Produces source rows + a boolean membership symbol
    (MAIN/sql/planner/plan/SemiJoinNode.java analog)."""

    source: PlanNode = None  # type: ignore[assignment]
    filter_source: PlanNode = None  # type: ignore[assignment]
    #: (source symbol, filter-source symbol) equi pairs
    keys: list[tuple[str, str]] = field(default_factory=list)
    match_symbol: str = ""
    #: residual predicate over (source row, filter-source row) pairs —
    #: correlated non-equi conjuncts from EXISTS subqueries (the
    #: reference plans these as correlated-join filters)
    filter: RowExpression | None = None
    #: True for IN-subquery semantics (3-valued NULL handling); False
    #: for EXISTS, which is always TRUE/FALSE (reference distinguishes
    #: these via SemiJoinNode vs CorrelatedJoin rewrites)
    null_aware: bool = False

    @property
    def sources(self):
        return [self.source, self.filter_source]


@dataclass
class SortKey:
    symbol: str
    ascending: bool = True
    nulls_first: bool | None = None


@dataclass
class WindowCall:
    """One window function over the node's shared window specification
    (MAIN/sql/planner/plan/WindowNode.Function analog)."""

    name: str  # row_number/rank/dense_rank/ntile/lead/lag/first_value/
    #          last_value/sum/avg/count/count_all/min/max
    args: tuple[RowExpression, ...]
    type: T.DataType
    #: (mode, start, end) with bounds ("unbounded_preceding"|"preceding"
    #: |"current"|"following"|"unbounded_following", offset|None);
    #: None = the SQL default frame (RANGE UNBOUNDED PRECEDING..CURRENT)
    frame: tuple | None = None

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.args))})"


@dataclass
class Window(PlanNode):
    """Adds window-function columns; row-preserving
    (MAIN/operator/WindowOperator.java analog). All functions of one
    node share the same PARTITION BY / ORDER BY."""

    source: PlanNode = None  # type: ignore[assignment]
    partition_by: list[str] = field(default_factory=list)
    order_keys: list[SortKey] = field(default_factory=list)
    #: output symbol -> window call (args are symbols of source)
    functions: dict[str, WindowCall] = field(default_factory=dict)

    @property
    def sources(self):
        return [self.source]


@dataclass
class Unnest(PlanNode):
    """Expand ARRAY constructors into rows (UnnestOperator analog,
    MAIN/operator/unnest/UnnestOperator.java). Each entry of ``arrays``
    is one ARRAY[...] argument's element expressions (over source
    symbols); multiple arrays zip, shorter ones NULL-pad (Trino
    semantics). The fan-out is static (len of the longest array), so
    the expansion is one fixed-shape reshape — the TPU-native form."""

    source: PlanNode = None  # type: ignore[assignment]
    arrays: list[tuple] = field(default_factory=list)
    element_symbols: list[str] = field(default_factory=list)

    @property
    def sources(self):
        return [self.source]


@dataclass
class GroupId(PlanNode):
    """Replicates the input once per grouping set with a set-id column;
    key columns not in a copy's set are NULLed (the
    MAIN/sql/planner/plan/GroupIdNode.java /
    MAIN/operator/GroupIdOperator.java analog). In the batch model the
    replication is one device concat of k masked copies — the
    aggregation above groups on (id, all keys), so rows of different
    sets can never collide even when a NULLed key meets a real NULL."""

    source: PlanNode = None  # type: ignore[assignment]
    #: one list of key symbols per grouping set
    grouping_sets: list[list[str]] = field(default_factory=list)
    id_symbol: str = "$groupid"

    @property
    def sources(self):
        return [self.source]


@dataclass
class Union(PlanNode):
    """UNION ALL: concatenation of sources
    (MAIN/sql/planner/plan/UnionNode.java analog). Distinct set
    semantics are planned as an Aggregate above, INTERSECT/EXCEPT as a
    marker column + group filter."""

    all_sources: list[PlanNode] = field(default_factory=list)
    #: output symbol -> per-source input symbols (one per source)
    symbol_map: dict[str, list[str]] = field(default_factory=dict)

    @property
    def sources(self):
        return list(self.all_sources)


@dataclass
class Sort(PlanNode):
    source: PlanNode = None  # type: ignore[assignment]
    keys: list[SortKey] = field(default_factory=list)

    @property
    def sources(self):
        return [self.source]


@dataclass
class TopN(PlanNode):
    source: PlanNode = None  # type: ignore[assignment]
    count: int = 0
    keys: list[SortKey] = field(default_factory=list)

    @property
    def sources(self):
        return [self.source]


@dataclass
class Limit(PlanNode):
    source: PlanNode = None  # type: ignore[assignment]
    count: int = 0
    offset: int = 0

    @property
    def sources(self):
        return [self.source]


@dataclass
class Values(PlanNode):
    rows: list[list] = field(default_factory=list)


@dataclass
class Exchange(PlanNode):
    """Repartitioning boundary inserted by the optimizer
    (MAIN/sql/planner/plan/ExchangeNode.java analog). scope=REMOTE
    becomes an ICI all_to_all / all_gather; scope=LOCAL a host-side
    reshard."""

    source: PlanNode = None  # type: ignore[assignment]
    partitioning: str = "single"  # single | hash | broadcast | range | source
    hash_symbols: list[str] = field(default_factory=list)
    scope: str = "REMOTE"
    #: whether the source subtree executes distributed ("dist") or as a
    #: single local page ("single") — set by plan.distribute
    input_dist: str = "dist"
    #: range partitioning (distributed ORDER BY): rows route to shards
    #: by sampled splitters of the FIRST sort key, so per-shard sorts
    #: concatenate into global order (the merge-exchange analog,
    #: MAIN/operator/MergeOperator.java / MergeSortedPages.java)
    sort_keys: list["SortKey"] | None = None
    #: single-gather of range-sorted shards: concatenation preserves
    #: the global order (no coordinator re-sort)
    ordered: bool = False

    @property
    def sources(self):
        return [self.source]


@dataclass
class Output(PlanNode):
    source: PlanNode = None  # type: ignore[assignment]
    #: user-facing column names in order
    names: list[str] = field(default_factory=list)
    symbols: list[str] = field(default_factory=list)

    @property
    def sources(self):
        return [self.source]


@dataclass
class TableWriter(PlanNode):
    """Drains its source into a connector WriteSink
    (MAIN/sql/planner/plan/TableWriterNode.java /
    MAIN/operator/TableWriterOperator.java analog). Emits one row per
    sealed fragment: ($rows, $bytes, $fragment) — the fragment strings
    ride the exchange fabric up to TableFinish, so a distributed write
    is just another stage whose (tiny) output spools with first-commit-
    wins attempt dedup, giving exactly-once fragment selection for
    free."""

    source: PlanNode = None  # type: ignore[assignment]
    #: JSON-safe connector write handle: {catalog, schema, table, mode,
    #: columns: [[name, type_str], ...], partition_by, ...} produced by
    #: Connector.begin_insert/begin_create (side-effect free)
    handle: dict = field(default_factory=dict)
    #: source symbols in target-table column order (position i feeds
    #: handle["columns"][i])
    columns: list[str] = field(default_factory=list)

    @property
    def sources(self):
        return [self.source]


@dataclass
class TableFinish(PlanNode):
    """Single-task commit stage above the writers
    (MAIN/sql/planner/plan/TableFinishNode.java /
    MAIN/operator/TableFinishOperator.java analog): gathers the winning
    attempts' fragment rows and calls Connector.finish_write exactly
    once. Output: a single-row ($written) count."""

    source: PlanNode = None  # type: ignore[assignment]
    handle: dict = field(default_factory=dict)

    @property
    def sources(self):
        return [self.source]


def plan_tree_str(node: PlanNode, indent: int = 0) -> str:
    """EXPLAIN-style rendering (MAIN/sql/planner/planprinter analog)."""
    pad = "  " * indent
    name = type(node).__name__
    detail = ""
    if isinstance(node, TableScan):
        detail = f"[{node.catalog}.{node.schema}.{node.table}]"
    elif isinstance(node, Filter):
        detail = f"[{node.predicate!r}]"
    elif isinstance(node, Project):
        detail = "[" + ", ".join(f"{k} := {v!r}" for k, v in node.assignments.items()) + "]"
    elif isinstance(node, Aggregate):
        detail = f"[{node.step} keys={node.group_keys} aggs=" + \
            ", ".join(f"{k}:={v!r}" for k, v in node.aggregates.items()) + "]"
    elif isinstance(node, Join):
        detail = f"[{node.kind} {node.criteria}" + (
            f" filter={node.filter!r}" if node.filter else "") + "]"
    elif isinstance(node, SemiJoin):
        detail = f"[{node.keys} -> {node.match_symbol}]"
    elif isinstance(node, (Sort, TopN)):
        ks = ", ".join(f"{k.symbol} {'asc' if k.ascending else 'desc'}" for k in node.keys)
        n = f" n={node.count}" if isinstance(node, TopN) else ""
        detail = f"[{ks}{n}]"
    elif isinstance(node, Limit):
        detail = f"[{node.count}]"
    elif isinstance(node, Window):
        ks = ", ".join(
            f"{k.symbol} {'asc' if k.ascending else 'desc'}"
            for k in node.order_keys
        )
        detail = (
            f"[partition={node.partition_by} order=[{ks}] fns="
            + ", ".join(f"{k}:={v!r}" for k, v in node.functions.items())
            + "]"
        )
    elif isinstance(node, Union):
        detail = f"[{len(node.all_sources)} branches]"
    elif isinstance(node, GroupId):
        detail = f"[{node.grouping_sets} -> {node.id_symbol}]"
    elif isinstance(node, Exchange):
        detail = f"[{node.scope} {node.partitioning} {node.hash_symbols}]"
    elif isinstance(node, Output):
        detail = f"[{node.names}]"
    elif isinstance(node, (TableWriter, TableFinish)):
        h = node.handle
        pb = h.get("partition_by") or []
        detail = (
            f"[{h.get('catalog', '')}.{h.get('schema', '')}."
            f"{h.get('table', '')} {h.get('mode', '')}"
            + (f" partition_by={pb}" if pb else "") + "]"
        )
    lines = [f"{pad}{name}{detail} -> {list(node.outputs)}"]
    for s in node.sources:
        lines.append(plan_tree_str(s, indent + 1))
    return "\n".join(lines)
