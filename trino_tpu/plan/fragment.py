"""Plan fragmentation for fleet mode: cut a distributed plan into a
stage DAG at exchange boundaries.

The analog of the reference's PlanFragmenter
(MAIN/sql/planner/PlanFragmenter.java:91): the optimizer's exchanged
plan (plan.distribute.add_exchanges) is cut at every repartitioning
boundary into fragments; each fragment becomes a stage whose tasks run
the fragment on workers with leaf ``RemoteSource`` nodes standing for
upstream stage outputs read from the spooled exchange (exec.spool).

Differences from the in-process mesh executor (exec.mesh): the mesh
lowers exchanges to ICI collectives inside one program; fleet mode
lowers them to durable hash-partitioned spool files crossing worker
processes (the DCN/FTE tier, SURVEY.md §5.8). PARTITIONED joins —
which the mesh repartitions internally — get explicit cut points here:
both children become hash stages on the join keys so the join fragment
reads co-partitioned inputs.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from dataclasses import replace as dc_replace

from trino_tpu.plan import nodes as P

__all__ = ["StageInput", "Stage", "fragment_plan", "salt_stage"]


@dataclass
class StageInput:
    """One RemoteSource of a stage: where its pages come from."""

    source_id: str
    stage_id: str
    #: "aligned" — task p reads partition p (hash exchange);
    #: "all" — every task reads the producer's full output (gather /
    #: broadcast)
    mode: str
    #: the producing stage's hash-partition keys (aligned mode): a
    #: mesh-owning worker re-exchanges the partition locally on these
    #: so its shards are key-disjoint (fleet x mesh composition)
    hash_symbols: list[str] = field(default_factory=list)


@dataclass
class Stage:
    stage_id: str
    root: P.PlanNode
    #: how THIS stage's output lands in the spool: "hash" over
    #: ``hash_symbols`` into n_partitions buckets, or "single" (one
    #: bucket — gather and broadcast consumers read it whole)
    partitioning: str
    hash_symbols: list[str] = field(default_factory=list)
    inputs: list[StageInput] = field(default_factory=list)
    #: SALTED exchange mode (coordinator skew mitigation): when set,
    #: ``{"source": source_id, "factor": K, "hot": [partition, ...]}``
    #: — each hot input partition is read by K tasks instead of one;
    #: the named input fans its rows out across the K salts (each task
    #: keeps a disjoint 1/K row slice) while every OTHER aligned input
    #: is replicated to all K salt tasks. Hot keys therefore spread
    #: over K workers with results identical to the unsalted plan
    #: (the SkewedPartitionRebalancer generalized to the read side of
    #: a join exchange).
    salt_plan: dict | None = None
    #: output partition count override (runtime-adaptive repartitioning,
    #: RuntimeAdaptivePartitioningRewriter analog): 0 = the fleet
    #: default; set by the coordinator before admission when an input
    #: edge blew past its cardinality estimate. Consumers size their
    #: aligned task lists from their producers' effective value.
    out_partitions: int = 0

    def scans(self) -> list[P.TableScan]:
        out = []

        def walk(n):
            if isinstance(n, P.TableScan):
                out.append(n)
            for s in n.sources:
                walk(s)

        walk(self.root)
        return out

    @property
    def aligned(self) -> bool:
        return any(i.mode == "aligned" for i in self.inputs)


def fragment_plan(plan: P.PlanNode) -> list[Stage]:
    """Cut an exchanged plan into stages, children before parents.
    The last stage is the root (single output partition)."""
    f = _Fragmenter()
    root = f.build(plan, "single", [])
    assert f.stages[-1] is root
    return f.stages


class _Fragmenter:
    def __init__(self):
        self.stages: list[Stage] = []
        self._ids = itertools.count()

    def build(
        self, node: P.PlanNode, partitioning: str, hash_symbols: list[str]
    ) -> Stage:
        stage = Stage(
            stage_id=str(next(self._ids)), root=None,
            partitioning=partitioning, hash_symbols=list(hash_symbols),
        )
        stage.root = self._cut(node, stage)
        self.stages.append(stage)
        return stage

    def _remote(self, stage: Stage, child: Stage, outputs, mode: str):
        sid = f"rs{child.stage_id}"
        stage.inputs.append(
            StageInput(
                sid, child.stage_id, mode,
                hash_symbols=list(child.hash_symbols),
            )
        )
        return P.RemoteSource(dict(outputs), source_id=sid)

    def _cut(self, node: P.PlanNode, stage: Stage) -> P.PlanNode:
        if isinstance(node, P.Exchange):
            if node.partitioning == "hash":
                child = self.build(node.source, "hash", node.hash_symbols)
                return self._remote(stage, child, node.outputs, "aligned")
            if node.partitioning == "round_robin":
                # scaled unpartitioned writers: rows spread evenly
                # across task_writer_count tasks, no key
                child = self.build(node.source, "round_robin", [])
                return self._remote(stage, child, node.outputs, "aligned")
            # single (gather) and broadcast both spool to one bucket;
            # the consumer-side difference is only which tasks read it
            child = self.build(node.source, "single", [])
            return self._remote(stage, child, node.outputs, "all")
        if isinstance(node, P.Join) and node.distribution == "PARTITIONED":
            lkeys = [a for a, _ in node.criteria]
            rkeys = [b for _, b in node.criteria]
            lchild = self.build(node.left, "hash", lkeys)
            rchild = self.build(node.right, "hash", rkeys)
            return dc_replace(
                node,
                left=self._remote(stage, lchild, node.left.outputs, "aligned"),
                right=self._remote(stage, rchild, node.right.outputs, "aligned"),
            )
        # descend
        from trino_tpu.plan.optimizer import _replace_sources

        srcs = [self._cut(s, stage) for s in node.sources]
        if srcs:
            node = _replace_sources(node, srcs)
        return node


def salt_stage(
    stage: Stage, source_id: str, factor: int, hot: list[int]
) -> Stage:
    """Rewrite ``stage`` in place to read ``source_id`` as a salted
    exchange: each hot partition fans out across ``factor`` salt tasks
    (the named input split by row slice, all other aligned inputs
    replicated). The fragment itself is untouched — salting changes
    only which rows each task reads, so plan wire format, operator
    shapes, and results are identical to the unsalted stage. Raises
    ``ValueError`` on a structurally impossible salt plan; semantic
    eligibility (only mergeable operators above the salted join) is
    ``plan.distribute.fragment_saltable``'s call, enforced again by
    ``plan.validate.validate_stages``."""
    declared = {i.source_id: i for i in stage.inputs}
    inp = declared.get(source_id)
    if inp is None or inp.mode != "aligned":
        raise ValueError(
            f"stage {stage.stage_id}: salted source {source_id!r} is "
            f"not an aligned input"
        )
    if int(factor) < 2:
        raise ValueError(f"salt factor must be >= 2, got {factor}")
    hot_sorted = sorted({int(p) for p in hot})
    if not hot_sorted or hot_sorted[0] < 0:
        raise ValueError(f"bad hot partition list {hot!r}")
    stage.salt_plan = {
        "source": source_id,
        "factor": int(factor),
        "hot": hot_sorted,
    }
    return stage
