"""Logical plan optimizer.

The analog of the reference's optimizer pipeline
(MAIN/sql/planner/PlanOptimizers.java:355-530), reduced to the passes
that matter for a batch-synchronous TPU engine:

- ``extract_joins``: rewrites Filter-over-cross-join chains (comma
  syntax) into equi-join trees, greedily connecting relations so no
  cross product remains (PredicatePushDown + join-graph planning; the
  reference's ReorderJoins CBO is approximated by smallest-first
  greedy growth using connector row counts).
- ``push_predicates``: moves single-side conjuncts below joins and
  through projects down to the scans (PredicatePushDown,
  PushPredicateIntoTableScan).
- ``prune_columns``: removes unused symbols so table scans only read
  referenced columns (PruneUnreferencedOutputs / applyProjection).
- ``choose_build_side``: flips inner joins so the estimated-smaller
  input is the build side (DetermineJoinDistributionType's
  size-based flip, sans exchange costing).

Each pass is a pure tree rewrite; the pipeline runs them in a fixed
order (the reference's IterativeOptimizer fixpoint machinery is not
needed at this scale).
"""

from __future__ import annotations

from dataclasses import replace as dc_replace

from trino_tpu import types as T
from trino_tpu.expr.ir import (
    Call,
    Cast,
    InputRef,
    Literal,
    RowExpression,
    join_key_compatible,
)
from trino_tpu.metadata import Metadata, Session
from trino_tpu.plan import nodes as P

__all__ = ["optimize"]


def _passes(metadata: Metadata, session: Session):
    """The pipeline as (pass name, rewrite) pairs — named so the
    per-pass sanity checker can attribute a broken invariant to the
    rewrite that introduced it (PlanSanityChecker's
    validateIntermediatePlan seam)."""
    from trino_tpu import session_properties as SP

    passes = [
        ("merge_adjacent_filters",
         lambda p: _rewrite_bottom_up(p, _merge_adjacent_filters)),
        ("factor_filter_ors",
         lambda p: _rewrite_bottom_up(p, _factor_filter_ors)),
        ("extract_joins",
         lambda p: _rewrite_bottom_up(
             p, lambda n: _extract_joins(n, metadata))),
        ("push_predicates", lambda p: _push_predicates(p, metadata)),
    ]
    if SP.get(session, "join_reordering_strategy") != "NONE":
        passes += [
            ("reorder_inner_joins",
             lambda p: _reorder_inner_joins(p, metadata)),
            # residual conjuncts hoisted by the reorder re-push onto
            # the new tree
            ("push_predicates(post-reorder)",
             lambda p: _push_predicates(p, metadata)),
        ]
    passes += [
        ("push_semijoin_filters",
         lambda p: _rewrite_bottom_up(p, _push_semijoin_filters)),
        ("choose_build_sides",
         lambda p: _choose_build_sides(p, metadata)),
        ("prune_columns", lambda p: _prune_columns(p)),
        ("annotate_scan_domains",
         lambda p: _rewrite_bottom_up(p, _annotate_scan_domains)),
    ]
    return passes


def optimize(plan: P.PlanNode, metadata: Metadata, session: Session) -> P.PlanNode:
    from trino_tpu.plan import validate as V

    check = V.level(session)
    if check == "FULL":
        # the analyzer's output is the baseline every pass is judged
        # against — a violation here is the analyzer's, not a pass's
        V.validate_plan(plan, phase="analyze")
    for name, rewrite in _passes(metadata, session):
        plan = rewrite(plan)
        if check == "FULL":
            V.validate_plan(plan, phase=name)
    if check == "FINAL":
        V.validate_plan(plan, phase="optimize(final)")
    return plan


def _annotate_scan_domains(node: P.PlanNode) -> P.PlanNode:
    """Derive TupleDomain-lite intervals from Filter-over-scan
    conjuncts and annotate the TableScan (the applyFilter pushdown,
    SPI/connector/ConnectorMetadata.java applyFilter +
    SPI/predicate/TupleDomain.java): comparisons of a scanned column
    against a literal become per-column [lo, hi] bounds the connector
    may prune storage units with. The Filter stays in place — pruning
    is advisory, never subsuming."""
    from trino_tpu.expr.compiler import _literal_device_value

    if not isinstance(node, P.Filter) or not isinstance(
        node.source, P.TableScan
    ):
        return node
    scan = node.source
    domains: dict[str, list] = {}
    _MIRROR = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le", "eq": "eq"}
    for conj in _conjuncts(node.predicate):
        if not (isinstance(conj, Call) and conj.name in _MIRROR):
            continue
        a, b = conj.args
        op = conj.name
        if isinstance(a, Literal) and isinstance(b, InputRef):
            a, b = b, a
            op = _MIRROR[op]
        if not (isinstance(a, InputRef) and isinstance(b, Literal)):
            continue
        if b.value is None or a.name not in scan.assignments:
            continue
        try:
            v = _literal_device_value(b)
        except Exception:
            continue
        cname = scan.assignments[a.name]
        dom = domains.setdefault(cname, [None, None, False, False])
        if op in ("gt", "ge"):
            if dom[0] is None or v >= dom[0]:
                dom[0], dom[2] = v, op == "gt"
        elif op in ("lt", "le"):
            if dom[1] is None or v <= dom[1]:
                dom[1], dom[3] = v, op == "lt"
        else:  # eq
            dom[0], dom[2] = v, False
            dom[1], dom[3] = v, False
    if not domains:
        return node
    return dc_replace(
        node,
        source=dc_replace(
            scan, domains={c: tuple(d) for c, d in domains.items()}
        ),
    )


def _merge_adjacent_filters(node: P.PlanNode) -> P.PlanNode:
    """Collapse Filter(Filter(x)) chains (the analyzer emits one Filter
    per WHERE conjunct) so join extraction sees every conjunct at once."""
    if not isinstance(node, P.Filter):
        return node
    preds = _conjuncts(node.predicate)
    src = node.source
    while isinstance(src, P.Filter):
        preds = _conjuncts(src.predicate) + preds
        src = src.source
    if src is node.source:
        return node
    return P.Filter(dict(node.outputs), source=src, predicate=_and_all(preds))


def _factor_filter_ors(node: P.PlanNode) -> P.PlanNode:
    """Extract conjuncts common to every OR branch:
    (A and B) or (A and C) -> A and (B or C)
    (ExtractCommonPredicatesExpressionRewriter analog). TPC-DS q13/q48
    repeat their equi-join conditions inside every OR branch — without
    factoring, join extraction sees no top-level equi conjuncts and
    plans a cross join."""
    if not isinstance(node, P.Filter):
        return node
    new_pred = _factor_or_common(node.predicate)
    if new_pred is node.predicate:
        return node
    return dc_replace(node, predicate=new_pred)


def _factor_or_common(e: RowExpression) -> RowExpression:
    if not isinstance(e, Call):
        return e
    if e.name == "and":
        args = tuple(_factor_or_common(a) for a in e.args)
        return Call(e.type, "and", args) if args != e.args else e
    if e.name != "or":
        return e
    branches = _disjuncts(e)
    if len(branches) < 2:
        return e
    conj_sets = [
        {repr(c): c for c in _conjuncts(_factor_or_common(b))}
        for b in branches
    ]
    common_keys = set(conj_sets[0])
    for s in conj_sets[1:]:
        common_keys &= set(s)
    if not common_keys:
        return e
    # keep a stable order: first branch's conjunct order
    common = [
        conj_sets[0][k] for k in conj_sets[0] if k in common_keys
    ]
    rests = []
    for s in conj_sets:
        rest = [c for k, c in s.items() if k not in common_keys]
        if not rest:
            # one branch is exactly the common part: the OR is
            # implied by it — the whole predicate reduces to common
            return _and_all(common)
        rests.append(_and_all(rest))
    out = _and_all(common + [_or_all(rests)])
    return out


def _disjuncts(e: RowExpression) -> list[RowExpression]:
    if isinstance(e, Call) and e.name == "or":
        out = []
        for a in e.args:
            out.extend(_disjuncts(a))
        return out
    return [e]


def _or_all(parts: list[RowExpression]) -> RowExpression:
    out = parts[0]
    for p in parts[1:]:
        out = Call(T.BOOLEAN, "or", (out, p))
    return out


# ---- generic walking -------------------------------------------------------

def _replace_sources(node: P.PlanNode, new_sources: list[P.PlanNode]) -> P.PlanNode:
    if isinstance(node, (P.Filter, P.Project, P.Aggregate, P.Sort, P.TopN,
                         P.Limit, P.Output, P.Exchange, P.Window,
                         P.Unnest, P.GroupId, P.TableWriter,
                         P.TableFinish)):
        return dc_replace(node, source=new_sources[0])
    if isinstance(node, P.Union):
        return dc_replace(node, all_sources=list(new_sources))
    if isinstance(node, P.Join):
        return dc_replace(node, left=new_sources[0], right=new_sources[1])
    if isinstance(node, P.SemiJoin):
        return dc_replace(
            node, source=new_sources[0], filter_source=new_sources[1]
        )
    return node


def _rewrite_bottom_up(node: P.PlanNode, fn) -> P.PlanNode:
    srcs = node.sources
    if srcs:
        node = _replace_sources(
            node, [_rewrite_bottom_up(s, fn) for s in srcs]
        )
    return fn(node)


def _conjuncts(e: RowExpression) -> list[RowExpression]:
    if isinstance(e, Call) and e.name == "and":
        out = []
        for a in e.args:
            out.extend(_conjuncts(a))
        return out
    return [e]


def _disjuncts(e: RowExpression) -> list[RowExpression]:
    if isinstance(e, Call) and e.name == "or":
        out = []
        for a in e.args:
            out.extend(_disjuncts(a))
        return out
    return [e]


def _and_all(parts: list[RowExpression]) -> RowExpression | None:
    if not parts:
        return None
    if len(parts) == 1:
        return parts[0]
    return Call(T.BOOLEAN, "and", tuple(parts))


def _refs(e: RowExpression) -> set[str]:
    if isinstance(e, InputRef):
        return {e.name}
    out: set[str] = set()
    if isinstance(e, Call):
        for a in e.args:
            out |= _refs(a)
    elif isinstance(e, Cast):
        out |= _refs(e.arg)
    return out


# ---- join extraction -------------------------------------------------------

def _flatten_cross(node: P.PlanNode) -> list[P.PlanNode] | None:
    """Flatten a pure cross-join tree into its relation list."""
    if isinstance(node, P.Join) and node.kind == "cross" and not node.criteria:
        out = []
        for s in (node.left, node.right):
            sub = _flatten_cross(s)
            out.extend(sub if sub is not None else [s])
        return out
    return None


def _extract_joins(node: P.PlanNode, metadata: Metadata) -> P.PlanNode:
    """Filter(cross-join chain) -> connected equi-join tree, ordered by
    estimated cardinality.

    The ReorderJoins/DetermineJoinDistributionType analog
    (MAIN/sql/planner/iterative/rule/ReorderJoins.java:97): instead of
    enumerating all orders through a memo, the tree grows greedily by
    cost — start from the connected pair with the smallest estimated
    join output, then repeatedly join in the connected relation whose
    addition yields the smallest estimated intermediate result (stats
    from plan.stats: connector row counts, NDVs, predicate
    selectivity). Deep TPC-DS trees (q72/q95) depend on this: syntactic
    order joins the largest fact tables first."""
    from trino_tpu.plan.stats import estimate

    if not isinstance(node, P.Filter):
        return node
    rels = _flatten_cross(node.source)
    if rels is None or len(rels) < 2:
        return node
    conjuncts = _hoist_or_common(_conjuncts(node.predicate))
    rel_syms = [set(r.outputs) for r in rels]

    def owner_of(refs: set[str]) -> list[int]:
        return [i for i, syms in enumerate(rel_syms) if refs & syms]

    # single-relation conjuncts stay as filters on that relation
    local: dict[int, list[RowExpression]] = {}
    equi: list[tuple[RowExpression, int, int, str, str]] = []
    residual: list[RowExpression] = []
    for c in conjuncts:
        refs = _refs(c)
        owners = owner_of(refs)
        if len(owners) == 1:
            local.setdefault(owners[0], []).append(c)
            continue
        pair = _equi_form(c, rel_syms)
        if pair is not None:
            i, j, ls, rs = pair
            equi.append((c, i, j, ls, rs))
        else:
            residual.append(c)

    parts: list[P.PlanNode] = list(rels)
    for i, preds in local.items():
        src = parts[i]
        parts[i] = P.Filter(
            dict(src.outputs), source=src, predicate=_and_all(preds)
        )

    tree, used_edges = _grow_join_tree(parts, equi, metadata)
    # equi edges whose endpoints landed in the same component earlier
    # than expected become residual comparisons
    for k, (c, *_rest) in enumerate(equi):
        if k not in used_edges:
            residual.append(c)
    if residual:
        tree = P.Filter(
            dict(tree.outputs), source=tree, predicate=_and_all(residual)
        )
    if set(tree.outputs) != set(node.outputs):
        tree = P.Project(
            dict(node.outputs),
            source=tree,
            assignments={
                s: InputRef(t, s) for s, t in node.outputs.items()
            },
        )
    return tree


def _grow_join_tree(
    parts: list[P.PlanNode],
    equi: list[tuple],
    metadata: Metadata,
) -> tuple[P.PlanNode, set[int]]:
    """Greedy cost-ordered join-tree growth over relations ``parts``
    and equi edges ``equi`` (entries (expr|None, i, j, left_sym,
    right_sym)). Starts from the connected pair with the smallest
    estimated join output, then repeatedly joins in the connected
    relation minimizing the estimated intermediate result. Returns
    (tree, consumed edge ids)."""
    from trino_tpu.plan.stats import estimate

    cache: dict = {}

    def rows(n: P.PlanNode) -> float:
        try:
            return estimate(n, metadata, cache).rows
        except Exception:
            return float("inf")

    def candidate(tree: P.PlanNode, placed: set[int], new: int):
        """Join(tree, parts[new]) using every unused equi edge between
        the placed set and `new`; returns (join, consumed edge ids)."""
        criteria, edges = [], []
        for k2, (_c2, i2, j2, ls2, rs2) in enumerate(equi):
            if k2 in used_edges:
                continue
            if {i2, j2} <= (placed | {new}) and new in (i2, j2):
                criteria.append((ls2, rs2) if j2 == new else (rs2, ls2))
                edges.append(k2)
        right = parts[new]
        join = P.Join(
            {**tree.outputs, **right.outputs},
            kind="inner" if criteria else "cross",
            left=tree, right=right, criteria=criteria,
        )
        return join, edges

    used_edges: set[int] = set()
    # starting pair: the connected pair with the smallest estimated
    # join output (ties: smaller combined inputs, then syntactic order)
    pair_ids = sorted({
        (min(i, j), max(i, j)) for _c, i, j, _ls, _rs in equi if i != j
    })
    if pair_ids:
        def pair_cost(p):
            i, j = p
            join, _ = candidate(parts[i], {i}, j)
            return (rows(join), rows(parts[i]) + rows(parts[j]), p)

        i0, j0 = min(pair_ids, key=pair_cost)
        tree, edges = candidate(parts[i0], {i0}, j0)
        used_edges.update(edges)
        placed = {i0, j0}
    else:
        placed = {0}
        tree = parts[0]
    remaining = set(range(len(parts))) - placed
    while remaining:
        connected = []
        for new in sorted(remaining):
            join, edges = candidate(tree, placed, new)
            if edges:
                connected.append((rows(join), new, join, edges))
        if connected:
            _, new, join, edges = min(
                connected, key=lambda t: (t[0], t[1])
            )
            tree = join
            used_edges.update(edges)
        else:
            # disconnected component: cross join, smallest first
            new = min(remaining, key=lambda r: (rows(parts[r]), r))
            right = parts[new]
            tree = P.Join(
                {**tree.outputs, **right.outputs},
                kind="cross", left=tree, right=right,
            )
        placed.add(new)
        remaining.remove(new)
    return tree, used_edges


def _reorder_inner_joins(node: P.PlanNode, metadata: Metadata) -> P.PlanNode:
    """Flatten maximal pure-inner-join subtrees (explicit JOIN ... ON
    syntax) into a relation set + equi-edge multigraph and regrow the
    tree by estimated cardinality (ReorderJoins.java:97's multi-join
    flattening). Runs after predicate pushdown so relation estimates
    see their filters. Non-equi join filters re-attach above the new
    tree — equivalent for inner joins."""
    def walk(n: P.PlanNode) -> P.PlanNode:
        srcs = n.sources
        if srcs:
            n = _replace_sources(n, [walk(s) for s in srcs])
        if not (
            isinstance(n, P.Join) and n.kind == "inner" and n.criteria
        ):
            return n
        parts: list[P.PlanNode] = []
        crits: list[tuple[str, str]] = []
        residual: list[RowExpression] = []

        def flatten(j: P.PlanNode):
            if isinstance(j, P.Join) and j.kind == "inner" and j.criteria:
                flatten(j.left)
                flatten(j.right)
                crits.extend(j.criteria)
                if j.filter is not None:
                    residual.extend(_conjuncts(j.filter))
            elif isinstance(j, P.Filter) and isinstance(j.source, P.Join) \
                    and j.source.kind == "inner" and j.source.criteria:
                # a residual (non-equi) filter parked on an inner join:
                # flatten through it; the conjuncts re-push after the
                # reorder (optimize runs _push_predicates again)
                residual.extend(_conjuncts(j.predicate))
                flatten(j.source)
            else:
                parts.append(j)

        flatten(n)
        if len(parts) < 3:
            return n
        rel_syms = [set(p.outputs) for p in parts]

        def owner(sym: str) -> int | None:
            for i, syms in enumerate(rel_syms):
                if sym in syms:
                    return i
            return None

        equi: list[tuple] = []
        for ls, rs in crits:
            i, j = owner(ls), owner(rs)
            if i is None or j is None or i == j:
                # criteria inside one relation (shouldn't happen) —
                # bail out, keep the original tree
                return n
            equi.append((None, i, j, ls, rs))
        tree, used_edges = _grow_join_tree(parts, equi, metadata)
        for k, (_c, _i, _j, ls, rs) in enumerate(equi):
            if k not in used_edges:
                lt = tree.outputs[ls]
                residual.append(Call(
                    T.BOOLEAN, "eq",
                    (InputRef(lt, ls), InputRef(tree.outputs[rs], rs)),
                ))
        out: P.PlanNode = _attach(tree, residual)
        if set(out.outputs) != set(n.outputs):
            out = P.Project(
                dict(n.outputs),
                source=out,
                assignments={
                    s: InputRef(t, s) for s, t in n.outputs.items()
                },
            )
        return out

    return walk(node)


def _hoist_or_common(conjuncts: list[RowExpression]) -> list[RowExpression]:
    """Factor conjuncts common to every OR branch up to the top level:
    (A and X) or (A and Y)  ==>  A and ((A and X) or (A and Y)).

    TPC-H q19 repeats its p_partkey = l_partkey equality inside each OR
    branch; without hoisting, join extraction sees no top-level equi
    edge and falls back to a cross product (the reference normalizes
    predicates the same way in PredicatePushDown's extractCommon)."""
    out = list(conjuncts)
    for c in conjuncts:
        if not (isinstance(c, Call) and c.name == "or"):
            continue
        branch_sets = [
            {repr(x): x for x in _conjuncts(b)} for b in _disjuncts(c)
        ]
        common = set(branch_sets[0])
        for bs in branch_sets[1:]:
            common &= set(bs)
        seen = {repr(x) for x in out}
        for key in common:
            if key not in seen:
                out.append(branch_sets[0][key])
    return out


def _equi_form(c: RowExpression, rel_syms: list[set[str]]):
    """symbol = symbol across two different relations."""
    if not (isinstance(c, Call) and c.name == "eq"):
        return None
    a, b = c.args
    if not (isinstance(a, InputRef) and isinstance(b, InputRef)):
        return None
    if not join_key_compatible(a.type, b.type):
        return None
    ia = [i for i, syms in enumerate(rel_syms) if a.name in syms]
    ib = [i for i, syms in enumerate(rel_syms) if b.name in syms]
    if len(ia) != 1 or len(ib) != 1 or ia[0] == ib[0]:
        return None
    return ia[0], ib[0], a.name, b.name


# ---- predicate pushdown ----------------------------------------------------

def _push_predicates(node: P.PlanNode, metadata: Metadata) -> P.PlanNode:
    return _push_node(node, [], metadata)


def _push_node(
    node: P.PlanNode, preds: list[RowExpression], metadata: Metadata
) -> P.PlanNode:
    """Push the given conjuncts (over node's outputs) below node when
    possible; re-attach the rest above."""
    if isinstance(node, P.Filter):
        return _push_node(
            node.source, preds + _conjuncts(node.predicate), metadata
        )
    if isinstance(node, P.Project):
        # push through when the conjunct only references pass-through
        # (identity) assignments
        identity = {
            s: e.name for s, e in node.assignments.items()
            if isinstance(e, InputRef)
        }
        pushable, kept = [], []
        for c in preds:
            refs = _refs(c)
            if refs <= set(identity):
                pushable.append(_rename(c, identity))
            else:
                kept.append(c)
        src = _push_node(node.source, pushable, metadata)
        out: P.PlanNode = dc_replace(node, source=src)
        return _attach(out, kept)
    if isinstance(node, P.Join):
        left_syms = set(node.left.outputs)
        right_syms = set(node.right.outputs)
        lp, rp, kept = [], [], []
        new_criteria = list(node.criteria)
        kind = node.kind
        for c in preds:
            refs = _refs(c)
            if refs <= left_syms and node.kind in ("inner", "left", "cross"):
                # left is the null-producing side of right/full joins;
                # pushing there would resurrect rows the filter drops
                lp.append(c)
            elif refs <= right_syms and node.kind in ("inner", "cross"):
                # right is the null-producing side of a left join: a
                # predicate there belongs above (it would drop the
                # null-extended rows if pushed)
                rp.append(c)
            elif node.kind in ("inner", "cross"):
                # equi predicate across the two sides joins them
                pair = _equi_form(c, [left_syms, right_syms])
                if pair is not None:
                    _, _, ls, rs = pair
                    new_criteria.append((ls, rs))
                    kind = "inner"
                else:
                    kept.append(c)
            else:
                kept.append(c)
        left = _push_node(node.left, lp, metadata)
        right = _push_node(node.right, rp, metadata)
        out = dc_replace(
            node, left=left, right=right, criteria=new_criteria, kind=kind
        )
        return _attach(out, kept)
    if isinstance(node, P.SemiJoin):
        src_syms = set(node.source.outputs)
        sp, kept = [], []
        for c in preds:
            if _refs(c) <= src_syms:
                sp.append(c)
            else:
                kept.append(c)
        src = _push_node(node.source, sp, metadata)
        filt = _push_node(node.filter_source, [], metadata)
        out = dc_replace(node, source=src, filter_source=filt)
        return _attach(out, kept)
    if isinstance(node, (P.Limit, P.Sort, P.TopN)):
        # filters do not commute with LIMIT; they do with SORT but
        # nothing generates that shape today — recurse without pushing
        src = _push_node(node.sources[0], [], metadata)
        return _attach(_replace_sources(node, [src]), preds)
    if isinstance(node, P.Aggregate):
        # conjuncts over group keys commute with the aggregation
        keys = set(node.group_keys)
        pushable = [c for c in preds if _refs(c) <= keys]
        kept = [c for c in preds if not (_refs(c) <= keys)]
        src = _push_node(node.source, pushable, metadata)
        return _attach(dc_replace(node, source=src), kept)
    if isinstance(node, (P.Output, P.Exchange)):
        src = _push_node(node.sources[0], preds, metadata)
        return _replace_sources(node, [src])
    # leaves (TableScan, Values) and anything unknown
    srcs = node.sources
    if srcs:
        node = _replace_sources(
            node, [_push_node(s, [], metadata) for s in srcs]
        )
    return _attach(node, preds)


def _attach(node: P.PlanNode, preds: list[RowExpression]) -> P.PlanNode:
    if not preds:
        return node
    return P.Filter(
        dict(node.outputs), source=node, predicate=_and_all(preds)
    )


def _rename(e: RowExpression, mapping: dict[str, str]) -> RowExpression:
    if isinstance(e, InputRef):
        return InputRef(e.type, mapping.get(e.name, e.name))
    if isinstance(e, Call):
        return Call(e.type, e.name, tuple(_rename(a, mapping) for a in e.args))
    if isinstance(e, Cast):
        return Cast(e.type, _rename(e.arg, mapping))
    return e


# ---- semi-join pushdown ----------------------------------------------------

def _push_semijoin_filters(node: P.PlanNode) -> P.PlanNode:
    """Push Filter(match)-over-SemiJoin through joins toward the side
    producing the semi-join keys.

    The analyzer plans an IN-subquery predicate as a SemiJoin ABOVE the
    query's join tree; left there, the engine materializes the full
    join output before discarding almost all of it (TPC-H Q18: 6M
    joined rows kept: ~600). A semi-join filter over one side's
    columns commutes with inner/cross joins (and with the probe side
    of left joins), exactly like a scalar predicate — the reference
    reaches the same shape through PredicatePushDown over
    SemiJoinNodes. The rewrite recurses so the filter lands directly
    on the key-producing relation."""
    if not (isinstance(node, P.Filter) and isinstance(node.source, P.SemiJoin)):
        return node
    sj = node.source
    conjs = _conjuncts(node.predicate)
    match_conj = next(
        (
            c for c in conjs
            if isinstance(c, InputRef) and c.name == sj.match_symbol
        ),
        None,
    )
    if match_conj is None:
        return node
    join = sj.source
    if not isinstance(join, P.Join):
        return node
    # symbols the semi-join needs from its source side
    need = {a for a, _ in sj.keys}
    if sj.filter is not None:
        need |= _refs(sj.filter) & set(join.outputs)
    for side in ("left", "right"):
        if join.kind == "cross":
            pass  # both sides eligible
        elif join.kind == "inner":
            pass
        elif join.kind == "left" and side == "left":
            pass  # probe side of a left join commutes
        else:
            continue
        child = getattr(join, side)
        if not need <= set(child.outputs):
            continue
        inner_sj = P.SemiJoin(
            {**child.outputs, sj.match_symbol: T.BOOLEAN},
            source=child,
            filter_source=sj.filter_source,
            keys=list(sj.keys),
            match_symbol=sj.match_symbol,
            filter=sj.filter,
            null_aware=sj.null_aware,
        )
        pushed = P.Filter(
            dict(child.outputs), source=inner_sj, predicate=match_conj
        )
        # keep pushing through nested joins
        pushed = _push_semijoin_filters(pushed)
        new_join = dc_replace(
            join,
            **{side: pushed},
            outputs={
                s: t for s, t in join.outputs.items()
                if s != sj.match_symbol
            },
        )
        rest = [c for c in conjs if c is not match_conj]
        return _attach(new_join, rest)
    return node


# ---- build-side choice -----------------------------------------------------

def _estimate_rows(node: P.PlanNode, metadata: Metadata) -> float:
    """Cardinality via the stats framework (plan.stats — the
    StatsCalculator analog: connector column stats + per-predicate
    selectivity instead of flat coefficients)."""
    from trino_tpu.plan.stats import estimate

    return estimate(node, metadata).rows


def _choose_build_sides(node: P.PlanNode, metadata: Metadata) -> P.PlanNode:
    from trino_tpu.plan.stats import estimate

    cache: dict = {}  # shared memo: one stats walk, not O(joins^2)

    def fn(n: P.PlanNode) -> P.PlanNode:
        if isinstance(n, P.Join) and n.kind == "inner" and n.criteria:
            l = estimate(n.left, metadata, cache).rows
            r = estimate(n.right, metadata, cache).rows
            if r > l * 1.5:  # build side (right) should be the smaller
                return dc_replace(
                    n, left=n.right, right=n.left,
                    criteria=[(b, a) for a, b in n.criteria],
                )
        return n

    return _rewrite_bottom_up(node, fn)


# ---- column pruning --------------------------------------------------------

def _prune_columns(node: P.PlanNode) -> P.PlanNode:
    return _prune(node, None)


def _prune(node: P.PlanNode, needed: set[str] | None) -> P.PlanNode:
    """Rebuild the tree keeping only symbols in ``needed`` (None = all,
    used at the root)."""
    if isinstance(node, P.Output):
        src = _prune(node.source, set(node.symbols))
        return dc_replace(node, source=src)
    if needed is None:
        needed = set(node.outputs)

    if isinstance(node, P.TableScan):
        assignments = {
            s: c for s, c in node.assignments.items() if s in needed
        }
        if not assignments:
            # count(*)-style scans still need one column for row counts
            s, c = next(iter(node.assignments.items()))
            assignments = {s: c}
        outputs = {s: t for s, t in node.outputs.items() if s in assignments}
        return dc_replace(node, assignments=assignments, outputs=outputs)
    if isinstance(node, P.Filter):
        src_needed = needed | _refs(node.predicate)
        src = _prune(node.source, src_needed)
        return dc_replace(
            node, source=src,
            outputs={s: t for s, t in src.outputs.items() if s in needed or s in node.outputs},
        )
    if isinstance(node, P.Project):
        assignments = {
            s: e for s, e in node.assignments.items() if s in needed
        }
        src_needed = set()
        for e in assignments.values():
            src_needed |= _refs(e)
        src = _prune(node.source, src_needed)
        return P.Project(
            {s: e.type for s, e in assignments.items()},
            source=src, assignments=assignments,
        )
    if isinstance(node, P.Aggregate):
        aggs = {s: a for s, a in node.aggregates.items() if s in needed}
        src_needed = set(node.group_keys)
        for a in aggs.values():
            for arg in a.args:
                src_needed |= _refs(arg)
            if a.filter is not None:
                src_needed |= _refs(a.filter)
        src = _prune(node.source, src_needed)
        outputs = {s: t for s, t in node.outputs.items()
                   if s in needed or s in node.group_keys}
        outputs.update({s: a.type for s, a in aggs.items()})
        return dc_replace(node, source=src, aggregates=aggs, outputs=outputs)
    if isinstance(node, P.Join):
        src_needed = set(needed)
        for a, b in node.criteria:
            src_needed.add(a)
            src_needed.add(b)
        filter_refs: set[str] = set()
        if node.filter is not None:
            filter_refs = _refs(node.filter)
            src_needed |= filter_refs
        left = _prune(node.left, src_needed & set(node.left.outputs))
        right = _prune(node.right, src_needed & set(node.right.outputs))
        # the executor materializes exactly node.outputs for the joined
        # page, so residual-filter columns must stay in it
        outputs = {
            s: t for s, t in node.outputs.items()
            if s in needed or s in filter_refs
        }
        return dc_replace(node, left=left, right=right, outputs=outputs)
    if isinstance(node, P.SemiJoin):
        filter_refs = set() if node.filter is None else _refs(node.filter)
        src_needed = (
            needed | {a for a, _ in node.keys} | filter_refs
        ) - {node.match_symbol}
        filt_needed = {b for _, b in node.keys} | (
            filter_refs & set(node.filter_source.outputs)
        )
        src = _prune(node.source, src_needed & set(node.source.outputs))
        filt = _prune(node.filter_source, filt_needed)
        outputs = {s: t for s, t in node.outputs.items() if s in needed}
        return dc_replace(node, source=src, filter_source=filt, outputs=outputs)
    if isinstance(node, (P.Sort, P.TopN)):
        src_needed = needed | {k.symbol for k in node.keys}
        src = _prune(node.sources[0], src_needed)
        return _replace_sources(
            dc_replace(node, outputs={
                s: t for s, t in src.outputs.items()
                if s in needed or s in src_needed
            }),
            [src],
        )
    if isinstance(node, (P.Limit, P.Exchange)):
        src = _prune(node.sources[0], needed)
        return _replace_sources(
            dc_replace(node, outputs=dict(src.outputs)), [src]
        )
    if isinstance(node, P.Values):
        return node
    if isinstance(node, P.TableWriter):
        # the writer consumes exactly its column list — everything the
        # source produces beyond it is prunable
        src = _prune(node.source, set(node.columns))
        return dc_replace(node, source=src)
    if isinstance(node, P.TableFinish):
        src = _prune(node.source, set(node.source.outputs))
        return dc_replace(node, source=src)
    return node
