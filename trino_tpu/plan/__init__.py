from trino_tpu.plan.nodes import (
    Aggregate,
    Filter,
    Join,
    Limit,
    Output,
    PlanNode,
    Project,
    SemiJoin,
    Sort,
    TableScan,
    TopN,
)

__all__ = [
    "Aggregate",
    "Filter",
    "Join",
    "Limit",
    "Output",
    "PlanNode",
    "Project",
    "SemiJoin",
    "Sort",
    "TableScan",
    "TopN",
]
