"""Plan/expression JSON codec — the host-boundary serialization layer.

The analog of the reference's plan-fragment wire format (Jackson JSON
of PlanFragment + RowExpressions shipped in POST /v1/task bodies,
MAIN/server/TaskResource.java:135): inside a mesh nothing serializes
(device arrays ride collectives), but across PROCESS boundaries — the
coordinator/worker seam standing in for DCN — plans travel as plain
JSON. Deliberately not pickle: the wire format stays inspectable and
carries no code-execution surface.
"""

from __future__ import annotations

from trino_tpu import types as T
from trino_tpu.expr.ir import AggCall, Call, Cast, InputRef, Literal
from trino_tpu.plan import nodes as P

__all__ = ["plan_to_json", "plan_from_json"]


# ---- types -----------------------------------------------------------------

def _t(t: T.DataType | None):
    return None if t is None else str(t)


def _t_back(s):
    if s is None:
        return None
    if s == "unknown":
        return T.UNKNOWN
    return T.type_from_name(s)


# ---- expressions -----------------------------------------------------------

def _expr(e):
    if e is None:
        return None
    if isinstance(e, Literal):
        v = e.value
        if isinstance(v, tuple):
            # ARRAY literals carry a tuple of storage-form scalars
            v = list(v)
        elif not (v is None or isinstance(v, (bool, int, float, str))):
            raise TypeError(f"unserializable literal {v!r}")
        return {"k": "lit", "t": _t(e.type), "v": v}
    if isinstance(e, InputRef):
        return {"k": "ref", "t": _t(e.type), "n": e.name}
    if isinstance(e, Call):
        return {
            "k": "call", "t": _t(e.type), "n": e.name,
            "a": [_expr(a) for a in e.args],
        }
    if isinstance(e, Cast):
        return {"k": "cast", "t": _t(e.type), "a": _expr(e.arg)}
    raise TypeError(f"unserializable expression {type(e).__name__}")


def _expr_back(d):
    if d is None:
        return None
    k = d["k"]
    if k == "lit":
        v = d["v"]
        if isinstance(v, list):
            v = tuple(v)
        return Literal(_t_back(d["t"]), v)
    if k == "ref":
        return InputRef(_t_back(d["t"]), d["n"])
    if k == "call":
        return Call(
            _t_back(d["t"]), d["n"],
            tuple(_expr_back(a) for a in d["a"]),
        )
    if k == "cast":
        return Cast(_t_back(d["t"]), _expr_back(d["a"]))
    raise ValueError(f"bad expression kind {k!r}")


def _agg(a: AggCall):
    return {
        "n": a.name, "a": [_expr(x) for x in a.args], "t": _t(a.type),
        "d": a.distinct, "f": _expr(a.filter),
    }


def _agg_back(d):
    return AggCall(
        d["n"], tuple(_expr_back(x) for x in d["a"]), _t_back(d["t"]),
        distinct=d["d"], filter=_expr_back(d["f"]),
    )


def _outputs(node: P.PlanNode):
    return [[s, _t(t)] for s, t in node.outputs.items()]


def _outputs_back(lst):
    return {s: _t_back(t) for s, t in lst}


def _sort_keys(keys):
    return [[k.symbol, k.ascending, k.nulls_first] for k in keys]


def _sort_keys_back(lst):
    return [P.SortKey(s, a, nf) for s, a, nf in lst]


# ---- plan nodes ------------------------------------------------------------

def plan_to_json(node: P.PlanNode) -> dict:
    d = {"kind": type(node).__name__, "outputs": _outputs(node)}
    if isinstance(node, P.TableScan):
        d.update(
            catalog=node.catalog, schema=node.schema, table=node.table,
            assignments=list(node.assignments.items()),
            hash_varchar=node.hash_varchar,
        )
        if node.split is not None:
            d.update(split=list(node.split))
        if node.domains is not None:
            d.update(domains=[
                [c, list(dom)] for c, dom in node.domains.items()
            ])
        return d
    if isinstance(node, P.RemoteSource):
        d.update(source_id=node.source_id)
        return d
    if isinstance(node, P.Values):
        d.update(rows=node.rows)
        return d
    if isinstance(node, P.Filter):
        d.update(source=plan_to_json(node.source), predicate=_expr(node.predicate))
        return d
    if isinstance(node, P.Project):
        d.update(
            source=plan_to_json(node.source),
            assignments=[[s, _expr(e)] for s, e in node.assignments.items()],
        )
        return d
    if isinstance(node, P.Aggregate):
        d.update(
            source=plan_to_json(node.source),
            group_keys=list(node.group_keys),
            aggregates=[[s, _agg(a)] for s, a in node.aggregates.items()],
            step=node.step, est_groups=node.est_groups,
            key_ranges=(
                None if node.key_ranges is None
                else list(node.key_ranges.items())
            ),
        )
        return d
    if isinstance(node, P.Join):
        d.update(
            kind2=node.kind, left=plan_to_json(node.left),
            right=plan_to_json(node.right),
            criteria=[list(c) for c in node.criteria],
            filter=_expr(node.filter), distribution=node.distribution,
            df_range_keep=node.df_range_keep,
            df_keep_frac=node.df_keep_frac,
        )
        return d
    if isinstance(node, P.SemiJoin):
        d.update(
            source=plan_to_json(node.source),
            filter_source=plan_to_json(node.filter_source),
            keys=[list(k) for k in node.keys],
            match_symbol=node.match_symbol, filter=_expr(node.filter),
            null_aware=node.null_aware,
        )
        return d
    if isinstance(node, P.Window):
        d.update(
            source=plan_to_json(node.source),
            partition_by=list(node.partition_by),
            order_keys=_sort_keys(node.order_keys),
            functions=[
                [
                    s,
                    {
                        "n": c.name, "a": [_expr(a) for a in c.args],
                        "t": _t(c.type), "frame": c.frame,
                    },
                ]
                for s, c in node.functions.items()
            ],
        )
        return d
    if isinstance(node, P.Union):
        d.update(
            all_sources=[plan_to_json(s) for s in node.all_sources],
            symbol_map=[[s, list(v)] for s, v in node.symbol_map.items()],
        )
        return d
    if isinstance(node, P.GroupId):
        d.update(
            source=plan_to_json(node.source),
            grouping_sets=[list(st) for st in node.grouping_sets],
            id_symbol=node.id_symbol,
        )
        return d
    if isinstance(node, P.Unnest):
        d.update(
            source=plan_to_json(node.source),
            arrays=[
                [_expr(e) for e in a] if isinstance(a, tuple)
                else {"ref": _expr(a)}
                for a in node.arrays
            ],
            element_symbols=list(node.element_symbols),
        )
        return d
    if isinstance(node, (P.Sort, P.TopN)):
        d.update(source=plan_to_json(node.source), keys=_sort_keys(node.keys))
        if isinstance(node, P.TopN):
            d.update(count=node.count)
        return d
    if isinstance(node, P.Limit):
        d.update(
            source=plan_to_json(node.source), count=node.count,
            offset=node.offset,
        )
        return d
    if isinstance(node, P.Exchange):
        d.update(
            source=plan_to_json(node.source),
            partitioning=node.partitioning,
            hash_symbols=list(node.hash_symbols), scope=node.scope,
            input_dist=node.input_dist, ordered=node.ordered,
            sort_keys=(
                None if node.sort_keys is None
                else _sort_keys(node.sort_keys)
            ),
        )
        return d
    if isinstance(node, P.Output):
        d.update(
            source=plan_to_json(node.source), names=list(node.names),
            symbols=list(node.symbols),
        )
        return d
    if isinstance(node, P.TableWriter):
        d.update(
            source=plan_to_json(node.source), handle=dict(node.handle),
            columns=list(node.columns),
        )
        return d
    if isinstance(node, P.TableFinish):
        d.update(
            source=plan_to_json(node.source), handle=dict(node.handle),
        )
        return d
    raise TypeError(f"unserializable plan node {type(node).__name__}")


def plan_from_json(d: dict) -> P.PlanNode:
    kind = d["kind"]
    outputs = _outputs_back(d["outputs"])
    if kind == "TableScan":
        return P.TableScan(
            outputs, catalog=d["catalog"], schema=d["schema"],
            table=d["table"], assignments=dict(d["assignments"]),
            hash_varchar=d.get("hash_varchar"),
            split=(tuple(d["split"]) if d.get("split") else None),
            domains=(
                {c: tuple(dom) for c, dom in d["domains"]}
                if d.get("domains") else None
            ),
        )
    if kind == "RemoteSource":
        return P.RemoteSource(outputs, source_id=d["source_id"])
    if kind == "Values":
        return P.Values(outputs, rows=d["rows"])
    if kind == "Filter":
        return P.Filter(
            outputs, source=plan_from_json(d["source"]),
            predicate=_expr_back(d["predicate"]),
        )
    if kind == "Project":
        return P.Project(
            outputs, source=plan_from_json(d["source"]),
            assignments={s: _expr_back(e) for s, e in d["assignments"]},
        )
    if kind == "Aggregate":
        return P.Aggregate(
            outputs, source=plan_from_json(d["source"]),
            group_keys=list(d["group_keys"]),
            aggregates={s: _agg_back(a) for s, a in d["aggregates"]},
            step=d["step"], est_groups=d["est_groups"],
            key_ranges=(
                None if d["key_ranges"] is None
                else {s: tuple(r) for s, r in d["key_ranges"]}
            ),
        )
    if kind == "Join":
        return P.Join(
            outputs, kind=d["kind2"],
            left=plan_from_json(d["left"]),
            right=plan_from_json(d["right"]),
            criteria=[tuple(c) for c in d["criteria"]],
            filter=_expr_back(d["filter"]),
            distribution=d["distribution"],
            df_range_keep=d["df_range_keep"],
            df_keep_frac=d["df_keep_frac"],
        )
    if kind == "SemiJoin":
        return P.SemiJoin(
            outputs, source=plan_from_json(d["source"]),
            filter_source=plan_from_json(d["filter_source"]),
            keys=[tuple(k) for k in d["keys"]],
            match_symbol=d["match_symbol"],
            filter=_expr_back(d["filter"]), null_aware=d["null_aware"],
        )
    if kind == "Window":
        return P.Window(
            outputs, source=plan_from_json(d["source"]),
            partition_by=list(d["partition_by"]),
            order_keys=_sort_keys_back(d["order_keys"]),
            functions={
                s: P.WindowCall(
                    c["n"], tuple(_expr_back(a) for a in c["a"]),
                    _t_back(c["t"]),
                    frame=(
                        None if c["frame"] is None
                        else _frame_back(c["frame"])
                    ),
                )
                for s, c in d["functions"]
            },
        )
    if kind == "Union":
        return P.Union(
            outputs,
            all_sources=[plan_from_json(s) for s in d["all_sources"]],
            symbol_map={s: list(v) for s, v in d["symbol_map"]},
        )
    if kind == "GroupId":
        return P.GroupId(
            outputs, source=plan_from_json(d["source"]),
            grouping_sets=[list(st) for st in d["grouping_sets"]],
            id_symbol=d["id_symbol"],
        )
    if kind == "Unnest":
        return P.Unnest(
            outputs, source=plan_from_json(d["source"]),
            arrays=[
                _expr_back(a["ref"]) if isinstance(a, dict)
                else tuple(_expr_back(e) for e in a)
                for a in d["arrays"]
            ],
            element_symbols=list(d["element_symbols"]),
        )
    if kind == "Sort":
        return P.Sort(
            outputs, source=plan_from_json(d["source"]),
            keys=_sort_keys_back(d["keys"]),
        )
    if kind == "TopN":
        return P.TopN(
            outputs, source=plan_from_json(d["source"]),
            count=d["count"], keys=_sort_keys_back(d["keys"]),
        )
    if kind == "Limit":
        return P.Limit(
            outputs, source=plan_from_json(d["source"]),
            count=d["count"], offset=d["offset"],
        )
    if kind == "Exchange":
        return P.Exchange(
            outputs, source=plan_from_json(d["source"]),
            partitioning=d["partitioning"],
            hash_symbols=list(d["hash_symbols"]), scope=d["scope"],
            input_dist=d["input_dist"], ordered=d.get("ordered", False),
            sort_keys=(
                None if d.get("sort_keys") is None
                else _sort_keys_back(d["sort_keys"])
            ),
        )
    if kind == "Output":
        return P.Output(
            outputs, source=plan_from_json(d["source"]),
            names=list(d["names"]), symbols=list(d["symbols"]),
        )
    if kind == "TableWriter":
        return P.TableWriter(
            outputs, source=plan_from_json(d["source"]),
            handle=dict(d["handle"]), columns=list(d["columns"]),
        )
    if kind == "TableFinish":
        return P.TableFinish(
            outputs, source=plan_from_json(d["source"]),
            handle=dict(d["handle"]),
        )
    raise ValueError(f"bad plan node kind {kind!r}")


def _frame_back(frame):
    """Window frames are (mode, (kind, off), (kind, off)) tuples; JSON
    turns the tuples into lists."""
    mode, start, end = frame
    return (mode, tuple(start), tuple(end))
