"""Top-level query engine facade.

The analog of the reference's LocalQueryRunner
(MAIN/testing/LocalQueryRunner.java:263): the full pipeline — parse,
analyze, plan, execute — in one process without the HTTP layers. The
distributed runner builds on the same stages but fragments the plan and
executes over a device mesh.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from trino_tpu.analyzer.analyzer import Analyzer
from trino_tpu.connectors.tpch.connector import TpchConnector
from trino_tpu.exec.local import LocalExecutor
from trino_tpu.metadata import Metadata, Session
from trino_tpu.page import Page
from trino_tpu.plan import nodes as P
from trino_tpu.plan.optimizer import optimize
from trino_tpu.sql.parser import parse_statement

__all__ = ["QueryRunner", "QueryResult"]


@dataclass
class QueryResult:
    names: list[str]
    rows: list[tuple]
    #: True when the query had a top-level ORDER BY (rows are ordered)
    ordered: bool = False
    plan: P.PlanNode | None = field(default=None, repr=False)


class QueryRunner:
    """SQL in, rows out — the LocalQueryRunner analog. With a ``mesh``,
    plans are distribution-planned and executed SPMD over the device
    mesh (the DistributedQueryRunner analog,
    TESTING/DistributedQueryRunner.java:98)."""

    def __init__(
        self,
        metadata: Metadata | None = None,
        session: Session | None = None,
        mesh=None,
    ):
        self.metadata = metadata or Metadata()
        self.session = session or Session()
        self.mesh = mesh
        # one executor across queries: keeps the jit-program cache and
        # device-resident scanned tables warm (a Trino worker's lifetime)
        if mesh is not None:
            from trino_tpu.exec.mesh import MeshExecutor

            self.executor = MeshExecutor(self.metadata, self.session, mesh)
        else:
            self.executor = LocalExecutor(self.metadata, self.session)

    @staticmethod
    def tpch(schema: str = "tiny", mesh=None) -> "QueryRunner":
        """Runner with the TPC-H catalog mounted (TpchQueryRunner analog,
        testing/trino-tests/.../TpchQueryRunner.java:21)."""
        md = Metadata()
        md.register_catalog("tpch", TpchConnector())
        return QueryRunner(md, Session(catalog="tpch", schema=schema), mesh=mesh)

    def plan_sql(self, sql: str, optimized: bool = True) -> P.PlanNode:
        stmt = parse_statement(sql)
        analyzer = Analyzer(self.metadata, self.session)
        plan = analyzer.analyze(stmt)
        if optimized:
            plan = optimize(plan, self.metadata, self.session)
        if self.mesh is not None:
            from trino_tpu.plan.distribute import add_exchanges

            plan = add_exchanges(plan, self.metadata)
        return plan

    def execute_page(self, sql: str) -> tuple[P.PlanNode, Page]:
        plan = self.plan_sql(sql)
        return plan, self.executor.execute(plan)

    def execute(self, sql: str) -> QueryResult:
        plan, page = self.execute_page(sql)
        ordered = _has_order(plan)
        return QueryResult(
            names=list(page.names),
            rows=page.to_pylist(),
            ordered=ordered,
            plan=plan,
        )


def _has_order(plan: P.PlanNode) -> bool:
    node = plan
    while isinstance(node, (P.Output, P.Limit, P.Project)):
        node = node.sources[0]
    return isinstance(node, (P.Sort, P.TopN))
