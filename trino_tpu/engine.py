"""Top-level query engine facade.

The analog of the reference's LocalQueryRunner
(MAIN/testing/LocalQueryRunner.java:263): the full pipeline — parse,
analyze, plan, execute — in one process without the HTTP layers. The
distributed runner builds on the same stages but fragments the plan and
executes over a device mesh.

Statement dispatch mirrors the reference's DataDefinitionExecution vs
SqlQueryExecution split (MAIN/execution/): metadata statements (SHOW,
DESCRIBE, USE, SET SESSION) execute coordinator-side; EXPLAIN renders
the plan; EXPLAIN ANALYZE executes with per-node device timings (the
ExplainAnalyzeOperator analog, MAIN/operator/ExplainAnalyzeOperator.java).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field

from trino_tpu.analyzer.analyzer import Analyzer
from trino_tpu.connectors.tpch.connector import TpchConnector
from trino_tpu.exec.local import LocalExecutor
from trino_tpu.metadata import Metadata, Session
from trino_tpu.page import Page
from trino_tpu.plan import nodes as P
from trino_tpu.plan.optimizer import optimize
from trino_tpu.sql import ast
from trino_tpu.sql.parser import parse_statement

__all__ = ["QueryRunner", "QueryResult"]


@dataclass
class QueryResult:
    names: list[str]
    rows: list[tuple]
    #: True when the query had a top-level ORDER BY (rows are ordered)
    ordered: bool = False
    plan: P.PlanNode | None = field(default=None, repr=False)
    #: fleet fault-tolerance counters (QueryStats analog): how many
    #: task attempts were re-queued after a failure, how many backup
    #: attempts were hedged against stragglers, how many of those
    #: backups committed first, and how many evicted workers rejoined.
    #: Always 0 outside fleet mode; tests use them to prove a recovery
    #: path actually fired rather than the query quietly sailing past
    tasks_retried: int = 0
    tasks_speculated: int = 0
    speculation_wins: int = 0
    workers_readmitted: int = 0
    #: workers that live-joined the placement pool mid-query after
    #: announcing into the membership registry (elastic fleet)
    workers_joined: int = 0
    #: whole-statement re-executions under retry_policy=QUERY (each
    #: one ran under a fresh spool epoch); 0 when the first execution
    #: succeeded or the policy is NONE/TASK
    query_retries: int = 0
    #: skew mitigation counters (fleet tier): exchange edges the
    #: coordinator re-planned as SALTED after hot-partition detection
    #: (skew_salt_threshold), and stages whose output partition count
    #: was grown at runtime after an input edge blew past its
    #: cardinality estimate (adaptive_partition_growth_factor)
    salted_edges: int = 0
    adaptive_repartitions: int = 0
    #: memory governance (QueryStats peakUserMemoryReservation analog):
    #: the query's peak concurrent reservation, total and per node
    peak_memory_bytes: int = 0
    peak_memory_per_node: dict = field(default_factory=dict)
    #: stitched trace span tree (telemetry.Trace) for the whole query;
    #: None when tracing was not active for this statement
    trace: object = field(default=None, repr=False)
    #: per-stage aggregates (rows/bytes in+out, elapsed, retries, peak
    #: memory) — the single source EXPLAIN ANALYZE's stage lines render
    #: from; one pseudo-stage for local execution
    stage_stats: list = field(default_factory=list)
    #: per-task rows backing system.runtime.tasks
    task_stats: list = field(default_factory=list)
    #: elapsed split (QueryStats analog): wall-clock in the planner vs
    #: everything after it
    planning_ms: float = 0.0
    execution_ms: float = 0.0
    #: wall-clock decomposition into named buckets (queued, planning,
    #: compile, scan, compute, exchange, straggler slack, ...) plus the
    #: critical path — telemetry_analysis.compute_time_breakdown over
    #: the finished trace; None when tracing was not active
    time_breakdown: dict | None = field(default=None, repr=False)
    #: per-HLO-scope device-time attribution from a kernel_profile
    #: capture (kernel_profile.attribute summary); None unless the
    #: session property was ON/AUTO and the capture succeeded
    kernel_profile: dict | None = field(default=None, repr=False)
    #: per-query cache traffic (cache.CacheStats.as_dict()): result-tier
    #: hit/miss + bytes and device-tier hits/misses/bytes; None when
    #: both tiers were disabled for the statement
    cache_stats: dict | None = field(default=None, repr=False)

    @property
    def query_info(self) -> dict | None:
        """Post-hoc QueryInfo tree (stages → tasks → operators), the
        same JSON ``GET /v1/query/{id}`` served live. Operator roofline
        attribution resolves lazily on first access — XLA cost analysis
        runs only for queries whose profile is actually read."""
        info = getattr(self, "_query_info", None)
        if info is None:
            resolver = getattr(self, "_query_info_resolver", None)
            if resolver is not None:
                self._query_info = info = resolver()
                self._query_info_resolver = None
        return info

    def profile_json(self, indent: int | None = None) -> str:
        """The profile artifact bench.py --profile-dir writes."""
        import json

        info = dict(self.query_info or {})
        if self.time_breakdown is not None:
            info["time_breakdown"] = self.time_breakdown
        return json.dumps(
            info, indent=indent, default=str, sort_keys=True,
        )


class QueryRunner:
    """SQL in, rows out — the LocalQueryRunner analog. With a ``mesh``,
    plans are distribution-planned and executed SPMD over the device
    mesh (the DistributedQueryRunner analog,
    TESTING/DistributedQueryRunner.java:98)."""

    def __init__(
        self,
        metadata: Metadata | None = None,
        session: Session | None = None,
        mesh=None,
    ):
        self.metadata = metadata or Metadata()
        self.session = session or Session()
        self.mesh = mesh
        # statements execute serially per runner: the executor's scan
        # cache, jit cache and the session are shared mutable state
        # (the coordinator's per-query threads all funnel through here)
        self._lock = threading.RLock()
        # one executor across queries: keeps the jit-program cache and
        # device-resident scanned tables warm (a Trino worker's lifetime)
        if mesh is not None:
            from trino_tpu.exec.mesh import MeshExecutor

            self.executor = MeshExecutor(self.metadata, self.session, mesh)
        else:
            self.executor = LocalExecutor(self.metadata, self.session)
        # per-runner semantic result cache (cache.py): repeat statements
        # on one long-lived runner hit; unrelated runners never share.
        # The serving layer overrides this with its own shared instance
        from trino_tpu import cache as _cache
        from trino_tpu import session_properties

        self.result_cache = _cache.register_result_cache(
            _cache.SemanticResultCache(
                int(session_properties.get(
                    self.session, "result_cache_max_bytes"
                ))
            )
        )
        # performance sentry observes every statement this runner
        # completes (no-op when TRINO_TPU_SENTRY=0)
        from trino_tpu import sentry as _sentry

        _sentry.ensure_installed(self.metadata)

    @staticmethod
    def tpch(schema: str = "tiny", mesh=None) -> "QueryRunner":
        """Runner with the TPC-H catalog mounted (TpchQueryRunner analog,
        testing/trino-tests/.../TpchQueryRunner.java:21)."""
        md = Metadata()
        md.register_catalog("tpch", TpchConnector())
        return QueryRunner(md, Session(catalog="tpch", schema=schema), mesh=mesh)

    @staticmethod
    def tpcds(schema: str = "tiny", mesh=None) -> "QueryRunner":
        """Runner with the TPC-DS catalog mounted (the reference's
        TpcdsQueryRunner analog)."""
        from trino_tpu.connectors.tpcds.connector import TpcdsConnector

        md = Metadata()
        md.register_catalog("tpcds", TpcdsConnector())
        return QueryRunner(
            md, Session(catalog="tpcds", schema=schema), mesh=mesh
        )

    @staticmethod
    def parquet(
        root: str, schema: str = "default", mesh=None,
        catalog: str = "hive",
    ) -> "QueryRunner":
        """Runner over a parquet directory tree (the HiveQueryRunner
        analog): ``root/<schema>/<table>.parquet`` files or Hive-style
        ``root/<schema>/<table>/<key>=<value>/`` partition trees."""
        from trino_tpu.connectors.parquet import ParquetConnector

        md = Metadata()
        md.register_catalog(catalog, ParquetConnector(root))
        return QueryRunner(
            md, Session(catalog=catalog, schema=schema), mesh=mesh
        )

    # ---- planning --------------------------------------------------------

    def plan_stmt(self, stmt: ast.Statement, optimized: bool = True) -> P.PlanNode:
        """Analyze + optimize one statement, timed into the active
        query's planning span (when ``execute`` opened one)."""
        tracer = getattr(self, "_tracer", None)
        t_span = time.perf_counter()
        try:
            if tracer is not None:
                with tracer.span("planning", "planning",
                                 stmt=type(stmt).__name__):
                    return self._plan_stmt_inner(stmt, optimized)
            return self._plan_stmt_inner(stmt, optimized)
        finally:
            self._plan_ms = (
                getattr(self, "_plan_ms", 0.0)
                + (time.perf_counter() - t_span) * 1e3
            )

    def _plan_stmt_inner(
        self, stmt: ast.Statement, optimized: bool = True
    ) -> P.PlanNode:
        from trino_tpu import fault, session_properties

        t_plan = time.monotonic()
        # chaos seam: an armed `planner` fault models a transient
        # planning-infrastructure failure (retryable at the QUERY tier)
        fault.check("planner", tag=type(stmt).__name__)
        plan_delay = session_properties.get(
            self.session, "planning_delay_ms"
        )
        if plan_delay:
            time.sleep(plan_delay / 1e3)
        analyzer = Analyzer(self.metadata, self.session)
        plan = analyzer.analyze(stmt)
        if optimized:
            plan = optimize(plan, self.metadata, self.session)
        if self.mesh is not None and (
            not _has_arrays(plan)
            or getattr(self.mesh, "host_exchange", False)
        ):
            # ARRAY columns live in host pools whose handles cannot
            # shard over a device mesh yet: array-bearing plans execute
            # on the local paths with a mesh attached. Fleet exchanges
            # move pages through the host spool serde (which carries
            # list columns), so a mesh stand-in that advertises
            # host_exchange distributes them normally.
            from trino_tpu.plan.distribute import add_exchanges
            from trino_tpu.plan import validate as _validate

            plan = add_exchanges(
                plan, self.metadata,
                n_shards=self.mesh.devices.size, session=self.session,
                # writer fan-out needs host-side exchanges (the fleet
                # spool); a real device mesh gathers below the writer
                scaled_writers=bool(
                    getattr(self.mesh, "host_exchange", False)
                ),
            )
            if optimized and _validate.level(self.session) != "OFF":
                _validate.validate_plan(plan, phase="add_exchanges")
        if optimized:
            from trino_tpu.plan.stats import annotate

            plan = annotate(plan, self.metadata, self.session)
        if optimized and session_properties.get(
            self.session, "result_cache_enabled"
        ) and _write_handle(plan) is None:
            # semantic fingerprint of the OPTIMIZED tree (post-annotate,
            # so the hash covers what will actually execute); pure
            # read-side derivation, safe under plan_validation=FULL
            from trino_tpu import cache as _cache

            plan._semantic_hash = _cache.plan_digest(plan, self.session)
        max_plan_s = session_properties.parse_duration(
            session_properties.get(self.session, "query_max_planning_time")
        )
        if max_plan_s > 0 and time.monotonic() - t_plan > max_plan_s:
            from trino_tpu.tracker import QueryDeadlineExceededError

            raise QueryDeadlineExceededError(
                f"Query exceeded maximum planning time limit of "
                f"{max_plan_s:g}s [query_max_planning_time]"
            )
        return plan

    def plan_sql(self, sql: str, optimized: bool = True) -> P.PlanNode:
        return self.plan_stmt(parse_statement(sql), optimized=optimized)

    # ---- execution -------------------------------------------------------

    def execute_page(self, sql: str) -> tuple[P.PlanNode, Page]:
        plan = self.plan_sql(sql)
        return plan, self.executor.execute(plan)

    def execute(
        self, sql: str, cancel_event=None, query_id: str | None = None,
    ) -> QueryResult:
        from trino_tpu import session_properties

        with self._lock:
            self.executor.cancel_event = cancel_event
            # absolute execution deadline: boundary checks inside the
            # executor turn it into QueryDeadlineExceededError; the
            # coordinator's QueryTracker reaps queries that wedge
            # between boundaries
            max_exec_s = session_properties.parse_duration(
                session_properties.get(
                    self.session, "query_max_execution_time"
                )
            )
            self.executor.deadline = (
                time.monotonic() + max_exec_s if max_exec_s > 0 else None
            )
            query_id = query_id or uuid.uuid4().hex[:12]
            # per-query memory context: all executor reservations made
            # by this statement attribute to this query's subtree of
            # the pool (restored afterwards so ad-hoc executor use
            # keeps its default context)
            prev_ctx = self.executor.memory_ctx
            qctx = self.executor.memory_pool.query_context(query_id)
            self.executor.memory_ctx = qctx
            from trino_tpu import telemetry, tracker
            from trino_tpu.profiler import OperatorProfiler

            prev_tracer = getattr(self, "_tracer", None)
            prev_plan_ms = getattr(self, "_plan_ms", 0.0)
            tracer = telemetry.Tracer(query_id)
            self._tracer = tracer
            self._plan_ms = 0.0
            tracker.QUERY_INFO.begin(
                query_id, sql=sql, user=self.session.user
            )
            prev_prof = self.executor.profiler
            self.executor.profiler = prof = OperatorProfiler()
            from trino_tpu import cache as cache_mod

            prev_cstats = getattr(self.executor, "cache_stats", None)
            prev_self_cstats = getattr(self, "_cache_stats", None)
            cstats = cache_mod.CacheStats()
            self._cache_stats = cstats
            self.executor.cache_stats = cstats
            kp_mode = str(
                session_properties.get(self.session, "kernel_profile")
                or "OFF"
            ).upper()
            t0 = time.perf_counter()
            # compile-counter baseline: the delta attributes THIS
            # statement's backend compiles (hook is process-wide)
            comp0 = telemetry.compile_snapshot()
            error = None
            result = None
            try:
                if kp_mode in ("ON", "AUTO"):
                    # device-profile the statement; attribution lands
                    # on QueryResult.kernel_profile (and, for AUTO, on
                    # the slow-query record when the threshold fires)
                    from trino_tpu import kernel_profile

                    with kernel_profile.Capture(
                        trigger="session" if kp_mode == "ON" else "auto"
                    ) as kp_cap:
                        result = self._execute(sql)
                    result.kernel_profile = kp_cap.summary()
                else:
                    result = self._execute(sql)
                result.peak_memory_bytes = qctx.peak_bytes
                if qctx.peak_bytes:
                    result.peak_memory_per_node = {
                        self.executor.memory_pool.node_id: qctx.peak_bytes
                    }
                return result
            except Exception as e:
                error = f"{type(e).__name__}: {e}"
                raise
            finally:
                self.executor.cancel_event = None
                self.executor.deadline = None
                self.executor.memory_ctx = prev_ctx
                self.executor.profiler = prev_prof
                self.executor.cache_stats = prev_cstats
                self._cache_stats = prev_self_cstats
                if result is not None and result.cache_stats is None and (
                    cstats.result_hit is not None
                    or cstats.device_hits
                    or cstats.device_misses
                ):
                    result.cache_stats = cstats.as_dict()
                plan_ms = self._plan_ms
                self._tracer = prev_tracer
                self._plan_ms = prev_plan_ms
                elapsed_ms = (time.perf_counter() - t0) * 1e3
                state = "FAILED" if error else "FINISHED"
                telemetry.QUERIES_TOTAL.inc(state=state)
                node_id = self.executor.memory_pool.node_id
                # timings-only seal for the live registry; the lazy
                # QueryResult.query_info resolver is the path that pays
                # for XLA cost analysis
                op_stats = prof.finish(None)
                for _row in op_stats:
                    telemetry.OPERATOR_SELF_TIME.observe(
                        _row.get("self_ms", 0.0) / 1e3,
                        operator=_row.get("node_type", "?"),
                    )
                tracker.QUERY_INFO.finish(
                    query_id, state=state,
                    rows=len(result.rows) if result else None,
                    error=error,
                    peak_memory_bytes=qctx.peak_bytes,
                    operator_stats=op_stats,
                )
                if result is not None:
                    _ex, _prof, _qid = self.executor, prof, query_id
                    result._query_info_resolver = (
                        lambda: _local_query_info(_ex, _prof, _qid)
                    )
                comp1 = telemetry.compile_snapshot()
                compiles_delta = int(
                    comp1.get("compiles", 0) - comp0.get("compiles", 0)
                )
                compile_ms_delta = max(
                    (
                        comp1.get("compile_seconds", 0.0)
                        - comp0.get("compile_seconds", 0.0)
                    ) * 1e3,
                    0.0,
                )
                plan_digest = None
                fingerprint = None
                if result is not None and result.plan is not None:
                    from trino_tpu import history as history_mod
                    from trino_tpu import journal as journal_mod

                    try:
                        plan_digest = journal_mod.plan_digest(result.plan)
                    except Exception:
                        plan_digest = None
                    fingerprint = history_mod.session_fingerprint(
                        self.session
                    )
                if result is not None:
                    result.trace = tracer.finish()
                    result.planning_ms = plan_ms
                    result.execution_ms = max(elapsed_ms - plan_ms, 0.0)
                    from trino_tpu import telemetry_analysis

                    result.time_breakdown = (
                        telemetry_analysis.compute_time_breakdown(
                            result.trace, elapsed_ms, op_stats=op_stats,
                            compile_ms=compile_ms_delta,
                        )
                    )
                    if (
                        result.time_breakdown
                        and result.names == ["Query Plan"]
                        and result.stage_stats
                    ):
                        # local EXPLAIN ANALYZE (stage_stats filled by
                        # _explain; plain EXPLAIN has none yet): the
                        # breakdown footer rides the rendered plan
                        result.rows.extend(
                            (line,)
                            for line in telemetry_analysis
                            .format_breakdown(result.time_breakdown)
                        )
                        # sentry baseline footer — judged against
                        # history that does NOT yet include this run
                        # (completion fires below)
                        from trino_tpu import sentry as sentry_mod

                        _bf = sentry_mod.baseline_footer(
                            plan_digest, fingerprint or "",
                            elapsed_ms, result.time_breakdown,
                        )
                        if _bf:
                            result.rows.append((_bf,))
                    if not result.stage_stats:
                        # local execution is one pseudo-stage; the fleet
                        # runner fills real per-stage aggregates instead
                        result.stage_stats = [{
                            "stage_id": "local",
                            "tasks": 1,
                            "rows_in": 0,
                            "rows_out": len(result.rows),
                            "bytes_out": 0,
                            "elapsed_ms": elapsed_ms,
                            "retries": 0,
                            "peak_memory_bytes": qctx.peak_bytes,
                            "admission_wait_ms": 0.0,
                        }]
                    if not result.task_stats:
                        # mirror the (possibly _explain-provided)
                        # stage aggregate so system.runtime.tasks and
                        # stage_stats always report the same numbers
                        st = result.stage_stats[0]
                        result.task_stats = [{
                            "query_id": query_id,
                            "stage_id": st["stage_id"],
                            "task_id": f"{st['stage_id']}.0",
                            "attempt": 0,
                            "state": state,
                            "worker": node_id,
                            "elapsed_ms": st["elapsed_ms"],
                            "rows_in": st["rows_in"],
                            "rows_out": st["rows_out"],
                            "bytes_out": st["bytes_out"],
                            "peak_memory_bytes": st[
                                "peak_memory_bytes"
                            ],
                        }]
                listeners = getattr(self.metadata, "event_listeners", ())
                if listeners:
                    from trino_tpu.events import (
                        QueryCompletedEvent,
                        fire_query_completed,
                    )

                    fire_query_completed(listeners, QueryCompletedEvent(
                        query_id=query_id,
                        user=self.session.user,
                        sql=sql,
                        state=state,
                        elapsed_ms=elapsed_ms,
                        rows=len(result.rows) if result else 0,
                        error=error,
                        peak_memory_bytes=qctx.peak_bytes,
                        peak_memory_per_node=(
                            (node_id, qctx.peak_bytes),
                        ) if qctx.peak_bytes else (),
                        planning_ms=plan_ms,
                        execution_ms=max(elapsed_ms - plan_ms, 0.0),
                        cpu_ms=max(elapsed_ms - plan_ms, 0.0),
                        query_retries=(
                            result.query_retries if result else 0
                        ),
                        tasks_retried=(
                            result.tasks_retried if result else 0
                        ),
                        tasks_speculated=(
                            result.tasks_speculated if result else 0
                        ),
                        speculation_wins=(
                            result.speculation_wins if result else 0
                        ),
                        workers_readmitted=(
                            result.workers_readmitted if result else 0
                        ),
                        plan_digest=plan_digest,
                        session_fingerprint=fingerprint,
                        cache_hit_tier=(
                            "result"
                            if result is not None
                            and result.cache_stats
                            and (
                                result.cache_stats.get("result") or {}
                            ).get("hit")
                            else None
                        ),
                        compiles=compiles_delta,
                        time_breakdown=(
                            result.time_breakdown if result else None
                        ),
                        trace=result.trace if result else None,
                        task_stats=tuple(
                            result.task_stats if result else ()
                        ),
                    ))
                from trino_tpu.events import maybe_log_slow_query

                maybe_log_slow_query(
                    listeners, self.session, query_id, sql,
                    elapsed_ms, op_stats, state=state,
                    time_breakdown=(
                        result.time_breakdown if result else None
                    ),
                    kernel_profile=(
                        result.kernel_profile if result else None
                    ),
                )

    def _execute(self, sql: str) -> QueryResult:
        from trino_tpu import session_properties

        stmt = parse_statement(sql)
        if not isinstance(stmt, (ast.SessionSet, ast.SessionReset)):
            # inconsistent memory caps fail fast at statement time
            # (SET SESSION stays allowed so a bad combination can be
            # corrected)
            from trino_tpu.memory import validate_session_limits

            validate_session_limits(self.session)
            delay = session_properties.get(
                self.session, "execution_delay_ms"
            )
            if delay:
                # test wedge: a dead sleep reaches no cooperative
                # boundary — only the QueryTracker reaper (or the
                # post-sleep deadline check) can retire the query
                time.sleep(delay / 1e3)
        return self._execute_stmt(stmt)

    def _execute_stmt(self, stmt: ast.Statement) -> QueryResult:
        if isinstance(stmt, ast.Prepare):
            self.session.prepared[stmt.name.lower()] = stmt.statement
            return QueryResult(["result"], [("PREPARE",)])
        if isinstance(stmt, ast.ExecutePrepared):
            body = self.session.prepared.get(stmt.name.lower())
            if body is None:
                raise ValueError(f"prepared statement {stmt.name!r} not found")
            return self._execute_stmt(_bind_parameters(body, stmt.args))
        if isinstance(stmt, ast.Deallocate):
            self.session.prepared.pop(stmt.name.lower(), None)
            return QueryResult(["result"], [("DEALLOCATE",)])
        if isinstance(stmt, ast.Explain):
            return self._explain(stmt)
        if isinstance(stmt, ast.ShowCatalogs):
            return QueryResult(
                ["Catalog"],
                [(c,) for c in sorted(self.metadata.catalogs())],
            )
        if isinstance(stmt, ast.ShowSchemas):
            cat = stmt.catalog or self.session.catalog
            conn = self.metadata.connector(cat)
            return QueryResult(
                ["Schema"], [(s,) for s in sorted(conn.list_schemas())]
            )
        if isinstance(stmt, ast.ShowTables):
            cat = self.session.catalog
            schema = self.session.schema
            if stmt.schema:
                parts = stmt.schema
                schema = parts[-1]
                if len(parts) > 1:
                    cat = parts[0]
            conn = self.metadata.connector(cat)
            return QueryResult(
                ["Table"], [(t,) for t in sorted(conn.list_tables(schema))]
            )
        if isinstance(stmt, ast.DescribeTable):
            qt, schema = self.metadata.resolve_table(
                self.session, tuple(stmt.table)
            )
            return QueryResult(
                ["Column", "Type"],
                [(c, str(t)) for c, t in schema.columns],
            )
        if isinstance(stmt, ast.Use):
            parts = list(stmt.parts)
            if len(parts) == 2:
                self.session.catalog, self.session.schema = parts
            else:
                self.session.schema = parts[0]
            return QueryResult(["result"], [("USE",)])
        if isinstance(stmt, ast.CreateView):
            qualified = self._qualify(stmt.name)
            self.metadata.access_control.check_can_ddl(
                self.session.user, *qualified
            )
            cat, sch, tab = qualified
            try:
                exists = tab in self.metadata.connector(cat).list_tables(sch)
            except Exception:
                exists = False
            if exists:
                # a view shadowing a table would make SELECT and DML
                # see different objects (and a self-referencing body
                # would recurse at use)
                raise ValueError(
                    f"table {'.'.join(qualified)} already exists; "
                    "a view cannot shadow it"
                )
            # validate now: a view that cannot analyze must not store
            self.plan_stmt(stmt.query)
            self.metadata.create_view(
                qualified, stmt.query, or_replace=stmt.or_replace
            )
            return QueryResult(["result"], [("CREATE VIEW",)])
        if isinstance(stmt, ast.DropView):
            qualified = self._qualify(stmt.name)
            self.metadata.access_control.check_can_ddl(
                self.session.user, *qualified
            )
            if not self.metadata.drop_view(qualified) and not stmt.if_exists:
                raise KeyError(f"view not found: {'.'.join(stmt.name)}")
            return QueryResult(["result"], [("DROP VIEW",)])
        if isinstance(stmt, ast.Delete):
            return self._delete(stmt)
        if isinstance(stmt, ast.Update):
            return self._update(stmt)
        if isinstance(stmt, ast.SessionSet):
            from trino_tpu import session_properties as SP

            v = stmt.value
            val = getattr(v, "value", None)
            if val is None and hasattr(v, "text"):
                val = v.text
            SP.set_property(self.session, stmt.name, val)
            return QueryResult(["result"], [("SET SESSION",)])
        if isinstance(stmt, ast.SessionReset):
            from trino_tpu import session_properties as SP

            if stmt.name not in SP.SESSION_PROPERTIES:
                raise ValueError(
                    f"unknown session property: {stmt.name}"
                )
            self.session.properties.pop(stmt.name, None)
            return QueryResult(["result"], [("RESET SESSION",)])
        if isinstance(stmt, ast.ShowSession):
            from trino_tpu import session_properties as SP

            return QueryResult(
                ["name", "value", "default", "type", "description"],
                SP.show_rows(self.session),
            )
        if isinstance(stmt, ast.CreateTable):
            return self._create_table(stmt)
        if isinstance(stmt, ast.CreateTableAs):
            return self._create_table_as(stmt)
        if isinstance(stmt, ast.InsertInto):
            return self._insert(stmt)
        if isinstance(stmt, ast.DropTable):
            cat, sch, tab = self._qualify(stmt.name)
            self.metadata.access_control.check_can_ddl(
                self.session.user, cat, sch, tab
            )
            conn = self.metadata.connector(cat)
            if stmt.if_exists and tab not in conn.list_tables(sch):
                return QueryResult(["result"], [("DROP TABLE",)])
            conn.drop_table(sch, tab)
            self.executor.invalidate_scan(cat, sch, tab)
            return QueryResult(["result"], [("DROP TABLE",)])
        plan = self.plan_stmt(stmt)
        rcache, digest, tokens = self._result_cache_probe(plan)
        cstats = getattr(self, "_cache_stats", None)
        if rcache is not None:
            hit = rcache.get(digest, tokens)
            if hit is not None:
                if cstats is not None:
                    cstats.result_hit = True
                    cstats.result_bytes = hit.nbytes
                return QueryResult(
                    names=hit.names, rows=hit.rows,
                    ordered=hit.ordered, plan=plan,
                )
            if cstats is not None:
                cstats.result_hit = False
        tracer = getattr(self, "_tracer", None)
        exec_span = (
            tracer.span("execute", "execution") if tracer is not None
            else _NullCtx()
        )
        self.executor._defer_ok = True
        try:
            done = False
            with exec_span as _sp:
                # anchor compile-kind work (persistent-cache reads,
                # injected compile delays) under the local exec span —
                # the worker task loop does the same for fleet tasks
                from trino_tpu import jit_cache

                if _sp is not None:
                    jit_cache.set_active_span(_sp)
                for _attempt in range(8):
                    page = self.executor.execute(plan)
                    pend = getattr(page, "pending_flags", None)
                    if pend is None:
                        rows = page.to_pylist()
                        done = True
                        break
                    # deferred final-chain sync: the result transfer
                    # carries the overflow flags; a tripped capacity
                    # re-runs the query with the bumped (persisted) size
                    rows, flags = page.to_pylist(extra=pend[0])
                    if not self.executor.note_deferred_overflow(
                        (flags, pend[1], pend[2])
                    ):
                        done = True
                        break
            if not done:
                # never return rows from an overflowed execution
                raise RuntimeError(
                    "aggregation table overflow persisted through retries"
                )
        finally:
            self.executor._defer_ok = False
            from trino_tpu import jit_cache

            jit_cache.set_active_span(None)
        ordered = _has_order(plan)
        if rcache is not None:
            rcache.put(digest, list(page.names), rows, ordered, tokens)
        return QueryResult(
            names=list(page.names),
            rows=rows,
            ordered=ordered,
            plan=plan,
        )

    def _result_cache_probe(self, plan):
        """``(cache, digest, tokens)`` when this plan is result-
        cacheable under the current session; ``(None, None, None)``
        otherwise (property off, unserializable plan, or a scan over an
        uncacheable live connector)."""
        from trino_tpu import cache as cache_mod, session_properties

        if not session_properties.get(self.session, "result_cache_enabled"):
            return None, None, None
        digest = getattr(plan, "_semantic_hash", None)
        if digest is None:
            return None, None, None
        tokens = cache_mod.table_tokens(plan, self.metadata)
        if tokens is None:
            return None, None, None
        return self.result_cache, digest, tokens

    # ---- DDL / DML (DataDefinitionExecution + TableWriter analog,
    # MAIN/execution/CreateTableTask.java, MAIN/operator/TableWriterOperator.java)

    def _qualify(self, parts) -> tuple[str, str, str]:
        parts = list(parts)
        if len(parts) == 3:
            return parts[0], parts[1], parts[2]
        if len(parts) == 2:
            return self.session.catalog, parts[0], parts[1]
        return self.session.catalog, self.session.schema, parts[0]

    def _create_table(self, stmt: ast.CreateTable) -> QueryResult:
        from trino_tpu import types as T
        from trino_tpu.connectors.base import TableSchema

        cat, sch, tab = self._qualify(stmt.name)
        self.metadata.access_control.check_can_ddl(
            self.session.user, cat, sch, tab
        )
        conn = self.metadata.connector(cat)
        if stmt.if_not_exists and tab in conn.list_tables(sch):
            return QueryResult(["result"], [("CREATE TABLE",)])
        ts = TableSchema(
            tab,
            [(c, T.type_from_name(tn)) for c, tn in stmt.columns],
        )
        conn.create_table(sch, tab, ts)
        return QueryResult(["result"], [("CREATE TABLE",)])

    def _execute_write_stmt(self, stmt: ast.Statement) -> QueryResult:
        """INSERT ... SELECT / CTAS through the TableWriter plan path:
        the analyzer performs target resolution, access checks, and the
        side-effect-free ``begin_*``; all mutation happens in the
        TableFinish commit. The statement epoch tokens the write so a
        replayed commit is idempotent."""
        plan = self.plan_stmt(stmt)
        handle = _write_handle(plan)
        ex = self.executor
        epoch = uuid.uuid4().hex[:12]
        prev_ctx = getattr(ex, "write_ctx", None)
        ex.write_ctx = {"epoch": epoch, "task": "t0", "attempt": 0}
        try:
            page = ex.execute(plan)
            rows = page.to_pylist()
        except BaseException:
            if handle is not None:
                try:
                    self.metadata.connector(handle["catalog"]).abort_write(
                        handle, token=epoch
                    )
                except Exception:
                    pass
            raise
        finally:
            ex.write_ctx = prev_ctx
        return QueryResult(
            names=list(page.names), rows=rows, plan=plan,
        )

    def _create_table_as(self, stmt: ast.CreateTableAs) -> QueryResult:
        return self._execute_write_stmt(stmt)

    def _insert(self, stmt: ast.InsertInto) -> QueryResult:
        if stmt.rows is None:
            return self._execute_write_stmt(stmt)
        # VALUES fast path: literals evaluate host-side, but the
        # mutation still flows begin_insert -> sink -> finish_write so
        # every connector write shares one commit protocol
        from trino_tpu.exec import write as W

        cat, sch, tab = self._qualify(stmt.name)
        self.metadata.access_control.check_can_insert(
            self.session.user, cat, sch, tab
        )
        conn = self.metadata.connector(cat)
        ts = conn.table_schema(sch, tab)
        target_cols = stmt.columns or ts.column_names
        for row in stmt.rows:
            if len(row) != len(target_cols):
                raise ValueError(
                    f"INSERT row has {len(row)} values but "
                    f"{len(target_cols)} target columns"
                )
        rows = [
            tuple(
                _literal_value(e, ts.column_type(c))
                for e, c in zip(row, target_cols)
            )
            for row in stmt.rows
        ]
        # align to the table's column order, NULL-filling the rest
        idx = {c: i for i, c in enumerate(target_cols)}
        full_rows = [
            tuple(
                row[idx[c]] if c in idx else None
                for c, _ in ts.columns
            )
            for row in rows
        ]
        cols = _rows_to_columns(ts, ts.column_names, full_rows)
        handle = conn.begin_insert(sch, tab)
        handle["catalog"] = cat
        epoch = uuid.uuid4().hex[:12]
        sink = conn.write_sink(
            handle, {"epoch": epoch, "task": "t0", "attempt": 0}
        )
        try:
            if full_rows:
                sink.append(cols, len(full_rows))
            res = W.finish_sink(sink)
            n, _secs = W.commit_write(
                self.metadata, handle, res["fragments"], token=epoch
            )
        except BaseException:
            sink.abort()
            try:
                conn.abort_write(handle, token=epoch)
            except Exception:
                pass
            raise
        self.executor.invalidate_scan(cat, sch, tab)
        return QueryResult(["rows"], [(n,)])

    # ---- EXPLAIN ---------------------------------------------------------

    def _dml_rows(self, name, items):
        """Evaluate DML expressions per row IN TABLE ORDER: one
        ``SELECT e1, .., en FROM t`` (Project over the scan — row count
        and order preserved, single scan for predicate AND assignments)
        returning python rows."""
        q = ast.Query(
            select=ast.Select(
                items=[ast.SelectItem(e) for e in items],
                relations=[ast.TableRef(tuple(name))],
            ),
            with_=[],
        )
        plan = self.plan_stmt(q, optimized=False)
        page = self.executor.execute(plan)
        return page.to_pylist()

    def _delete(self, stmt: "ast.Delete") -> QueryResult:
        """Row-level DELETE (the MergeWriter family's delete case): the
        predicate evaluates device-side in table order; the connector
        rewrites its storage to the kept rows, rejecting the write if
        the table version moved underneath (conflict detection)."""
        import numpy as np

        cat, sch, tab = self._qualify(stmt.name)
        self.metadata.access_control.check_can_delete(
            self.session.user, cat, sch, tab
        )
        conn = self.metadata.connector(cat)
        version = conn.table_version(sch, tab)
        if stmt.where is None:
            keep = np.zeros(conn.row_count(sch, tab), dtype=bool)
        else:
            rows = self._dml_rows(stmt.name, [stmt.where])
            keep = ~np.asarray(
                [r[0] is True for r in rows], dtype=bool
            )
        n = conn.delete_rows(sch, tab, keep, expected_version=version)
        self.executor.invalidate_scan(cat, sch, tab)
        return QueryResult(["rows"], [(n,)])

    def _update(self, stmt: "ast.Update") -> QueryResult:
        """Row-level UPDATE: ONE query evaluates the predicate and
        every assignment expression together, then the connector
        overwrites the masked rows' columns in place (version-checked
        against concurrent writers)."""
        import numpy as np

        cat, sch, tab = self._qualify(stmt.name)
        self.metadata.access_control.check_can_update(
            self.session.user, cat, sch, tab
        )
        conn = self.metadata.connector(cat)
        version = conn.table_version(sch, tab)
        ts = conn.table_schema(sch, tab)
        cols = [c for c, _ in stmt.assignments]
        items = [e for _, e in stmt.assignments]
        if stmt.where is not None:
            items = items + [stmt.where]
        rows = self._dml_rows(stmt.name, items)
        if stmt.where is not None:
            mask = np.asarray(
                [r[-1] is True for r in rows], dtype=bool
            )
            rows = [r[:-1] for r in rows]
        else:
            mask = np.ones(len(rows), dtype=bool)
        new_cols = _rows_to_columns(ts, cols, rows)
        n = conn.update_rows(
            sch, tab, new_cols, mask, expected_version=version
        )
        self.executor.invalidate_scan(cat, sch, tab)
        return QueryResult(["rows"], [(n,)])

    def _explain(self, stmt: ast.Explain) -> QueryResult:
        plan = self.plan_stmt(stmt.statement)
        if not stmt.analyze:
            return QueryResult(
                ["Query Plan"],
                [(line,) for line in P.plan_tree_str(plan).splitlines()],
            )
        stats: dict[int, tuple[float, int]] = {}
        ex = self.executor
        orig = type(ex).execute

        def timed(node):
            t0 = time.perf_counter()
            out = orig(ex, node)
            # force completion so the timing covers device work (the
            # reference's operator wall clocks include the same sync
            # bias at pipeline boundaries)
            n_rows = out.num_rows() if hasattr(out, "num_rows") else 0
            stats[id(node)] = (
                (time.perf_counter() - t0) * 1e3, n_rows,
            )
            return out

        # instance-level patch: other executors (and other threads'
        # runners) are untouched
        ex.execute = timed
        xstats = getattr(ex, "exchange_stats", None)
        # snapshot-delta (never reset shared counters); histograms are
        # nested dicts, so deep-copy the edge maps for their delta
        x0 = dict(xstats) if xstats is not None else None
        p0 = {
            e: dict(h)
            for e, h in (
                (xstats or {}).get("partition_rows") or {}
            ).items()
        }
        skew0 = getattr(ex, "skew_joins", 0)
        esc0 = getattr(ex, "exchange_escalations", 0)
        # per-operator XLA cost attribution rides on the profiler the
        # surrounding execute() installed (EXPLAIN ANALYZE called
        # directly on a bare runner installs its own)
        own_prof = None
        if ex.profiler is None:
            from trino_tpu.profiler import OperatorProfiler

            ex.profiler = own_prof = OperatorProfiler()
        scan0 = len(getattr(ex, "scan_log", None) or [])
        # EXPLAIN ANALYZE executes for real; a write plan needs the
        # same commit token scoping (and failure abort) as execute()
        wh = _write_handle(plan)
        w_epoch = None
        if wh is not None:
            w_epoch = uuid.uuid4().hex[:12]
            ex.write_ctx = {"epoch": w_epoch, "task": "t0", "attempt": 0}
            ex.last_write_stats = None
            ex.last_commit_stats = None
        kp_cap = None
        try:
            t0 = time.perf_counter()
            if stmt.verbose:
                # VERBOSE tier: device-profile the run; to_pylist's
                # host sync keeps every dispatch inside the window
                from trino_tpu import kernel_profile

                with kernel_profile.Capture(trigger="explain") as kp_cap:
                    page = ex.execute(plan)
                    rows = page.to_pylist()
            else:
                page = ex.execute(plan)
                rows = page.to_pylist()
            total_ms = (time.perf_counter() - t0) * 1e3
        except BaseException:
            if wh is not None:
                try:
                    self.metadata.connector(wh["catalog"]).abort_write(
                        wh, token=w_epoch
                    )
                except Exception:
                    pass
            raise
        finally:
            del ex.execute
            if wh is not None:
                ex.write_ctx = None
        # seal records now (costs resolve through the persistent XLA
        # cache) and key them by plan node for the annotated tree;
        # EXPLAIN ANALYZE is an explicit profile request, so eager
        # cost analysis is the point, not overhead
        prof = ex.profiler
        profile: dict[int, dict] = {}
        try:
            prof.finish(ex)
            for rec in prof.records:
                profile[rec.plan_node_id] = rec.to_dict()
        finally:
            if own_prof is not None:
                ex.profiler = None
        # fold the per-node timings into the single local pseudo-stage's
        # aggregate: EXPLAIN ANALYZE's stage line, QueryResult.stage_stats
        # and system.runtime.tasks all render from this one dict
        from trino_tpu.exec.spill import row_bytes

        peak = getattr(ex, "memory_ctx", None)
        peak_bytes = peak.peak_bytes if peak is not None else 0
        rows_in = sum(
            stats[id(n)][1]
            for n in _walk_plan(plan)
            if not n.sources and id(n) in stats
        )
        stage_stats = [{
            "stage_id": "local",
            "tasks": 1,
            "rows_in": rows_in,
            "rows_out": len(rows),
            "bytes_out": len(rows) * row_bytes(plan.outputs),
            "elapsed_ms": total_ms,
            "retries": 0,
            "peak_memory_bytes": peak_bytes,
            "admission_wait_ms": 0.0,
        }]
        lines = [_stage_stats_line("Query", stage_stats[0])]
        if peak_bytes:
            # per-node peak reservations (QueryStats
            # peakUserMemoryReservation in EXPLAIN ANALYZE analog)
            lines.append(
                f"Peak memory: {_fmt_bytes(peak_bytes)} "
                f"({ex.memory_pool.node_id}: "
                f"{_fmt_bytes(peak_bytes)})"
            )
        cw = getattr(ex, "last_commit_stats", None)
        if wh is not None and cw is not None:
            # writer summary (rows/files/bytes from the committed
            # fragments; commit latency is the finish_write wall time)
            lines.append(
                f"TableWriter: {cw['rows']} rows, {cw['files']} files, "
                f"{_fmt_bytes(cw['bytes'])} "
                f"(commit {cw['commit_seconds'] * 1000.0:.1f} ms)"
            )
        _cs = getattr(self, "_cache_stats", None)
        if _cs is not None and (
            _cs.result_hit is not None
            or _cs.device_hits or _cs.device_misses
        ):
            # per-query cache traffic (hit/miss + bytes per tier); the
            # result tier never serves EXPLAIN ANALYZE itself (analyze
            # must execute) but its probe state still renders here
            lines.append(_cs.explain_line())
        if xstats is not None and xstats["exchanges"] > x0["exchanges"]:
            # distributed exchange telemetry (the reference surfaces
            # per-stage exchange bytes in EXPLAIN ANALYZE the same way)
            lines.append(
                f"Exchanges: {xstats['exchanges'] - x0['exchanges']} "
                f"all_to_all, "
                f"{_fmt_bytes(xstats['bytes'] - x0['bytes'])} moved, "
                f"skew-split joins: {getattr(ex, 'skew_joins', 0) - skew0}, "
                f"bucket escalations: "
                f"{getattr(ex, 'exchange_escalations', 0) - esc0}"
            )
        if xstats is not None:
            from trino_tpu import telemetry_analysis

            for edge, hist in sorted(
                (xstats.get("partition_rows") or {}).items()
            ):
                base = p0.get(edge, {})
                delta = {
                    p: int(v) - int(base.get(p, 0))
                    for p, v in hist.items()
                    if int(v) - int(base.get(p, 0)) > 0
                }
                skew = telemetry_analysis.partition_skew(delta)
                if skew["partitions"] > 1:
                    # per-edge shard routing skew (only recorded when
                    # the exchange_partition_counters debug sync is on)
                    lines.append(
                        f"Exchange {edge}: "
                        f"{skew['partitions']} partitions, "
                        f"max/mean {skew['max_mean_ratio']:.2f}, "
                        f"cv {skew['cv']:.2f}"
                    )
        for entry in (getattr(ex, "scan_log", None) or [])[scan0:]:
            # storage pushdown effectiveness (the connector-metrics
            # lines Trino's EXPLAIN ANALYZE renders per scan)
            parts = [
                f"Scan {entry.get('table', '?')}: "
                f"{entry.get('rowgroups_pruned', 0)}/"
                f"{entry.get('rowgroups_total', 0)} row groups pruned",
            ]
            if entry.get("partitions_pruned"):
                parts.append(
                    f"{entry['partitions_pruned']} partitions pruned"
                )
            if entry.get("streamed"):
                parts.append(
                    f"streamed in {entry.get('batches', 0)} batches"
                )
            lines.append(", ".join(parts))
        # kernel observatory: the programs this query dispatched, in
        # first-dispatch order (profiler records carry the jit keys)
        from trino_tpu import program_catalog, telemetry

        dispatched: list = []
        for rec in prof.records:
            for key in getattr(rec, "dispatch_keys", ()):
                if key not in dispatched:
                    dispatched.append(key)
        # satellite: memory_analysis() temp+output vs what the
        # MemoryContext actually reserved — the estimate-based
        # governor's error, surfaced per query and as a gauge
        est_bytes = 0
        for key in dispatched:
            m = program_catalog.CATALOG.memory(key)
            if m is not None:
                est_bytes += (m["temp_bytes"] or 0) + (
                    m["output_bytes"] or 0
                )
        if est_bytes and peak_bytes:
            ratio = est_bytes / peak_bytes
            telemetry.MEMORY_ESTIMATE_RATIO.set(ratio)
            lines.append(
                f"Compiled-program HBM: {_fmt_bytes(est_bytes)} "
                f"temp+output across {len(dispatched)} program(s) vs "
                f"{_fmt_bytes(peak_bytes)} reserved "
                f"(ratio {ratio:.2f})"
            )
        lines.extend(
            _annotated_tree(plan, stats, profile=profile).splitlines()
        )
        if stmt.verbose:
            # VERBOSE tier: per-HLO-scope device time inside the fused
            # programs, then each dispatched program's catalog entry
            summary = kp_cap.summary() if kp_cap is not None else None
            lines.append("Kernel profile (device time by HLO scope):")
            if summary and summary.get("scopes"):
                denom = (
                    summary["attributed_us"]
                    + summary["unattributed_us"]
                ) or 1.0
                for scope, us in summary["scopes"].items():
                    lines.append(
                        f"  {scope}: {us / 1e3:.3f} ms "
                        f"({us / denom * 100:.0f}%)"
                    )
                if summary["unattributed_us"]:
                    lines.append(
                        "  (unattributed): "
                        f"{summary['unattributed_us'] / 1e3:.3f} ms"
                    )
            else:
                lines.append("  <no attributable device events captured>")
            for key in dispatched:
                e = program_catalog.CATALOG.entry_for(key, resolve=True)
                if e is None:
                    continue
                flops = (
                    f"{e.flops:.0f}" if e.flops is not None else "?"
                )
                temp = (
                    _fmt_bytes(e.temp_bytes)
                    if e.temp_bytes is not None else "?"
                )
                lines.append(
                    f"  Program {e.program_id} [{e.label}] "
                    f"({e.source}): {flops} flops, temp {temp}, "
                    f"compile {e.compile_s * 1e3:.0f} ms, "
                    f"hits {e.hits}"
                )
        out = QueryResult(["Query Plan"], [(line,) for line in lines])
        out.stage_stats = stage_stats
        # EXPLAIN ANALYZE executed the inner statement for real, so it
        # carries the inner plan: the sentry digests it and the footer
        # compares against the plain statement's own baseline (plain
        # EXPLAIN stays plan-less — a planning-only wall clock must
        # never feed an execution baseline)
        out.plan = plan
        if kp_cap is not None:
            out.kernel_profile = kp_cap.summary()
        return out


def _local_query_info(executor, prof, query_id: str) -> dict:
    """Resolve the local engine's post-hoc QueryInfo tree: seal the
    profiler WITH the executor so operator records gain XLA cost /
    roofline attribution (the lazily-paid step), then shape the same
    single-pseudo-stage tree the live registry serves."""
    from trino_tpu import tracker
    from trino_tpu.profiler import tree_from_stats

    stats = prof.finish(executor)
    info = tracker.QUERY_INFO.get(query_id) or {
        "query_id": query_id, "state": "FINISHED", "stages": [],
    }
    info["stages"] = [{
        "stage_id": "local",
        "tasks": [{
            "task_id": "local-0",
            "attempt": 0,
            "state": info.get("state", "FINISHED"),
            "worker": "local",
            "operators": tree_from_stats(stats),
        }],
    }]
    return info


class _NullCtx:
    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return None


def _walk_plan(node: P.PlanNode):
    yield node
    for s in node.sources:
        yield from _walk_plan(s)


def _stage_stats_line(label: str, st: dict) -> str:
    """One EXPLAIN ANALYZE stage line rendered from a stage_stats dict
    (the single source both the local and fleet paths use)."""
    line = (
        f"{label}: {st['tasks']} task(s), in: {st['rows_in']} rows, "
        f"out: {st['rows_out']} rows ({_fmt_bytes(st['bytes_out'])}), "
        f"{st['elapsed_ms']:.1f} ms total"
    )
    if st.get("retries"):
        line += f", retries: {st['retries']}"
    if st.get("peak_memory_bytes"):
        line += f", peak memory: {_fmt_bytes(st['peak_memory_bytes'])}"
    if st.get("admission_wait_ms"):
        line += f", admission wait: {st['admission_wait_ms']:.1f} ms"
    if st.get("direct_bytes") or st.get("spooled_bytes"):
        line += (
            f", direct fetch ratio: {st.get('direct_fetch_ratio', 0.0):.2f}"
        )
    return line


def _timed_frontier_ms(node: P.PlanNode, stats) -> float:
    """Total time of the nearest timed descendants (fused interior
    nodes never pass through execute(), so the direct sources of a
    chain head are untimed — walk through them)."""
    total = 0.0
    for s in node.sources:
        if id(s) in stats:
            total += stats[id(s)][0]
        else:
            total += _timed_frontier_ms(s, stats)
    return total


def _rows_in(node: P.PlanNode, stats) -> int:
    """Input rows = nearest timed descendants' output rows (the
    OperatorStats inputPositions analog)."""
    total = 0
    for s in node.sources:
        if id(s) in stats:
            total += stats[id(s)][1]
        else:
            total += _rows_in(s, stats)
    return total


def _annotated_tree(
    node: P.PlanNode, stats, indent: int = 0, profile=None,
) -> str:
    from trino_tpu.exec.spill import row_bytes

    own = stats.get(id(node))
    base = P.plan_tree_str(node, indent).splitlines()[0]
    if own is not None:
        ms, n_rows = own
        child_ms = _timed_frontier_ms(node, stats)
        n_in = _rows_in(node, stats)
        out_bytes = n_rows * row_bytes(node.outputs)
        base += (
            f"   [in: {n_in} rows, out: {n_rows} rows"
            f" ({_fmt_bytes(out_bytes)}), "
            f"self: {max(ms - child_ms, 0.0):.1f} ms]"
        )
        prow = (profile or {}).get(id(node))
        if prow and prow.get("achieved_gflops") is not None:
            # the TPU-native column: measured rate vs the XLA cost
            # model's roofline ceiling for this compiled program
            util = prow.get("roofline_utilization")
            base += (
                f" [xla: {prow['flops'] / 1e6:.1f} MFLOPs, "
                f"{prow['achieved_gflops']:.2f} GFLOP/s achieved"
            )
            if util is not None:
                base += (
                    f", {util * 100:.1f}% of "
                    f"{prow['roofline_gflops']:.0f} GFLOP/s roofline"
                )
            base += "]"
    lines = [base]
    for s in node.sources:
        lines.append(_annotated_tree(s, stats, indent + 1, profile))
    return "\n".join(lines)


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if n < 1024 or unit == "GB":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024
    return f"{n}B"


def _has_arrays(plan: P.PlanNode) -> bool:
    from trino_tpu import types as T

    pooled = (T.ArrayType, T.MapType, T.RowType)
    if any(isinstance(t, pooled) for t in plan.outputs.values()):
        return True
    return any(_has_arrays(s) for s in plan.sources)


def _bind_parameters(stmt, args: list) -> "ast.Statement":
    """Deep-copy a prepared statement with each positional ? replaced
    by its EXECUTE ... USING argument expression (the reference binds
    in the analyzer; an AST substitution is equivalent for a fully
    constant-folded argument list)."""
    import copy

    def xform(v):
        if isinstance(v, ast.Parameter):
            if v.index >= len(args):
                raise ValueError(
                    f"prepared statement needs {v.index + 1} "
                    f"parameters, got {len(args)}"
                )
            return copy.deepcopy(args[v.index])
        if isinstance(v, ast.Node):
            for k, sub in vars(v).items():
                setattr(v, k, xform(sub))
            return v
        if isinstance(v, list):
            return [xform(x) for x in v]
        if isinstance(v, tuple):
            return tuple(xform(x) for x in v)
        return v

    return xform(copy.deepcopy(stmt))


# the host storage codec moved to connectors.base so the write path
# (exec/write.py, WriteSink implementations) shares one encoder with
# the legacy host-side VALUES path; these aliases keep engine-internal
# call sites and test imports stable
from trino_tpu.connectors.base import (  # noqa: E402
    _elem_storage,
    rows_to_columns as _rows_to_columns,
    to_unscaled as _to_unscaled,
)


def _literal_value(e: ast.Expr, t):
    """Evaluate an INSERT VALUES literal expression host-side."""
    if isinstance(e, ast.NullLit):
        return None
    if isinstance(e, (ast.IntLit, ast.FloatLit, ast.StrLit, ast.BoolLit)):
        return e.value
    if isinstance(e, ast.DecimalLit):
        from decimal import Decimal

        return Decimal(e.text)
    if isinstance(e, (ast.DateLit, ast.TimestampLit)):
        return e.text
    if (
        isinstance(e, ast.Unary)
        and e.op == "-"
        and isinstance(e.arg, (ast.IntLit, ast.FloatLit))
    ):
        return -e.arg.value
    if (
        isinstance(e, ast.Unary)
        and e.op == "-"
        and isinstance(e.arg, ast.DecimalLit)
    ):
        from decimal import Decimal

        return -Decimal(e.arg.text)
    if isinstance(e, ast.ArrayLit):
        from trino_tpu import types as T

        elem = t.element if isinstance(t, T.ArrayType) else None
        return [_literal_value(x, elem) for x in e.items]
    if isinstance(e, ast.FnCall) and e.name.lower() == "map":
        from trino_tpu import types as T

        if not (
            isinstance(t, T.MapType)
            and len(e.args) == 2
            and all(isinstance(a, ast.ArrayLit) for a in e.args)
        ):
            raise NotImplementedError(
                "INSERT map() takes (ARRAY[...], ARRAY[...])"
            )
        ks = [_literal_value(x, t.key) for x in e.args[0].items]
        vs = [_literal_value(x, t.value) for x in e.args[1].items]
        if len(ks) != len(vs):
            raise ValueError("map() key/value arrays differ in length")
        if len(set(ks)) != len(ks):
            # same rule as the analyzer's map constructor — INSERT
            # must not silently keep-first what SELECT rejects
            raise ValueError("Duplicate map keys are not allowed")
        return list(zip(ks, vs))
    if isinstance(e, ast.FnCall) and e.name.lower() == "row":
        from trino_tpu import types as T

        if not isinstance(t, T.RowType) or len(e.args) != len(t.fields):
            raise NotImplementedError(
                "INSERT row() arity must match the ROW type"
            )
        return tuple(
            _literal_value(x, ft) for x, (_fn, ft) in zip(e.args, t.fields)
        )
    raise NotImplementedError(
        f"INSERT VALUES supports literals only, got {type(e).__name__}"
    )


def _has_order(plan: P.PlanNode) -> bool:
    node = plan
    while isinstance(node, (P.Output, P.Limit, P.Project)):
        node = node.sources[0]
    return isinstance(node, (P.Sort, P.TopN))


def _write_handle(plan: P.PlanNode) -> dict | None:
    """The write handle of a TableFinish-rooted (DML) plan, else None.
    Write plans are never result-cached and commit with the statement
    epoch as idempotency token."""
    node = plan
    while isinstance(node, (P.Output, P.Exchange)):
        node = node.sources[0]
    if isinstance(node, P.TableFinish):
        return node.handle
    return None
