"""Query deadline governance: typed lifecycle errors + the reaper.

The analog of the reference's QueryTracker.enforceTimeLimits
(MAIN/execution/QueryTracker.java): a coordinator-side daemon sweeps
the live query set on a short period and *reaps* any query past its
deadline — QUEUED past ``query_max_queued_time`` or RUNNING past
``query_max_execution_time`` — marking it FAILED with a typed
``QueryDeadlineExceededError`` and firing its cancel event. The sweep
is what makes deadlines robust: a cooperative check inside the engine
covers the well-behaved path, but a *wedged* query (stuck in a kernel,
a sleep, a hung RPC) never reaches its next boundary check, and only
an external reaper can retire it. The reaper marks the query FAILED
immediately — the protocol surfaces the deadline error to clients even
while the wedged thread is still unwinding.

Deadline failures are terminal by definition (more attempts cannot
create more time), so both FTE tiers classify
``QueryDeadlineExceededError`` non-retryable, and
``QueryRetriesExhaustedError`` marks the QUERY tier giving up.
"""

from __future__ import annotations

import threading
import time

__all__ = [
    "QueryDeadlineExceededError", "QueryRetriesExhaustedError",
    "QueryTracker",
]


class QueryDeadlineExceededError(RuntimeError):
    """Query exceeded query_max_execution_time /
    query_max_planning_time / query_max_queued_time
    (EXCEEDED_TIME_LIMIT analog — never retried by either FTE tier)."""


class QueryRetriesExhaustedError(RuntimeError):
    """The QUERY retry tier ran out of attempts (or budget) without a
    successful execution; carries the last underlying failure."""


class QueryTracker:
    """Deadline reaper over a coordinator's live queries.

    Reads each QueryState's ``max_queued_s`` / ``max_exec_s``
    (captured from session properties at submit) against its
    ``created_at`` / ``started_at`` timestamps. Reaping a query:
    state -> FAILED with the typed error string, cancel event set (so
    a cooperative executor aborts at its next boundary), cancelled
    flag set, and the resource-group condition notified so a QUEUED
    query's dispatch thread unblocks promptly instead of waiting for
    an unrelated release.
    """

    def __init__(self, coordinator, period_s: float = 0.05):
        self.coordinator = coordinator
        self.period_s = period_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        #: (query_id, reason) log of reaped queries
        self.reaped: list[tuple[str, str]] = []

    def start(self) -> "QueryTracker":
        self._thread = threading.Thread(
            target=self._loop, name="query-tracker", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.period_s):
            try:
                self.sweep()
            except Exception:
                pass  # the reaper must outlive any one bad sweep

    def sweep(self):
        """One enforcement pass (callable directly from tests)."""
        now = time.time()
        with self.coordinator._lock:
            queries = list(self.coordinator._queries.values())
        for q in queries:
            if q.state == "QUEUED":
                limit = getattr(q, "max_queued_s", 0.0)
                if limit and now - q.created_at > limit:
                    self._reap(
                        q,
                        f"Query exceeded maximum queued time limit "
                        f"of {limit:g}s",
                        "queued",
                    )
            elif q.state == "RUNNING":
                limit = getattr(q, "max_exec_s", 0.0)
                started = getattr(q, "started_at", None) or q.created_at
                if limit and now - started > limit:
                    self._reap(
                        q,
                        f"Query exceeded maximum execution time limit "
                        f"of {limit:g}s",
                        "execution",
                    )

    def _reap(self, q, message: str, reason: str):
        if q.state in ("FINISHED", "FAILED"):
            return
        q.error = f"QueryDeadlineExceededError: {message}"
        q.state = "FAILED"
        q.finished_at = time.time()
        q.cancelled = True
        q.cancel_event.set()
        self.reaped.append((q.query_id, reason))
        # a QUEUED query's dispatch thread is blocked in acquire();
        # poke the condition so it observes cancellation now
        wakeup = getattr(self.coordinator.resource_groups, "wakeup", None)
        if wakeup is not None:
            wakeup()
