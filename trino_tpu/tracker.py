"""Query deadline governance: typed lifecycle errors + the reaper.

The analog of the reference's QueryTracker.enforceTimeLimits
(MAIN/execution/QueryTracker.java): a coordinator-side daemon sweeps
the live query set on a short period and *reaps* any query past its
deadline — QUEUED past ``query_max_queued_time`` or RUNNING past
``query_max_execution_time`` — marking it FAILED with a typed
``QueryDeadlineExceededError`` and firing its cancel event. The sweep
is what makes deadlines robust: a cooperative check inside the engine
covers the well-behaved path, but a *wedged* query (stuck in a kernel,
a sleep, a hung RPC) never reaches its next boundary check, and only
an external reaper can retire it. The reaper marks the query FAILED
immediately — the protocol surfaces the deadline error to clients even
while the wedged thread is still unwinding.

Deadline failures are terminal by definition (more attempts cannot
create more time), so both FTE tiers classify
``QueryDeadlineExceededError`` non-retryable, and
``QueryRetriesExhaustedError`` marks the QUERY tier giving up.
"""

from __future__ import annotations

import threading
import time

__all__ = [
    "QueryDeadlineExceededError", "QueryRetriesExhaustedError",
    "QueryTracker", "QueryInfoRegistry", "QUERY_INFO",
]


class QueryDeadlineExceededError(RuntimeError):
    """Query exceeded query_max_execution_time /
    query_max_planning_time / query_max_queued_time
    (EXCEEDED_TIME_LIMIT analog — never retried by either FTE tier)."""


class QueryRetriesExhaustedError(RuntimeError):
    """The QUERY retry tier ran out of attempts (or budget) without a
    successful execution; carries the last underlying failure."""


class QueryTracker:
    """Deadline reaper over a coordinator's live queries.

    Reads each QueryState's ``max_queued_s`` / ``max_exec_s``
    (captured from session properties at submit) against its
    ``created_at`` / ``started_at`` timestamps. Reaping a query:
    state -> FAILED with the typed error string, cancel event set (so
    a cooperative executor aborts at its next boundary), cancelled
    flag set, and the resource-group condition notified so a QUEUED
    query's dispatch thread unblocks promptly instead of waiting for
    an unrelated release.
    """

    def __init__(self, coordinator, period_s: float = 0.05):
        self.coordinator = coordinator
        self.period_s = period_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        #: (query_id, reason) log of reaped queries
        self.reaped: list[tuple[str, str]] = []
        import os

        #: journal GC cadence/TTL: entries terminal longer than the
        #: TTL are removed on the next due sweep (PR 18 shipped gc();
        #: this is the caller that keeps _journal/ bounded)
        self.journal_gc_period_s = float(
            os.environ.get("TRINO_TPU_JOURNAL_GC_PERIOD_S", "")
            or 60.0
        )
        self.journal_ttl_s = float(
            os.environ.get("TRINO_TPU_JOURNAL_TTL_S", "")
            or 7 * 24 * 3600.0
        )
        self._journal_gc_due = time.time() + self.journal_gc_period_s

    def start(self) -> "QueryTracker":
        self._thread = threading.Thread(
            target=self._loop, name="query-tracker", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _loop(self):
        while not self._stop.wait(self.period_s):
            try:
                self.sweep()
            except Exception:
                pass  # the reaper must outlive any one bad sweep

    def sweep(self):
        """One enforcement pass (callable directly from tests)."""
        now = time.time()
        self._maybe_gc_journal(now)
        with self.coordinator._lock:
            queries = list(self.coordinator._queries.values())
        for q in queries:
            if q.state == "QUEUED":
                limit = getattr(q, "max_queued_s", 0.0)
                if limit and now - q.created_at > limit:
                    self._reap(
                        q,
                        f"Query exceeded maximum queued time limit "
                        f"of {limit:g}s",
                        "queued",
                    )
            elif q.state == "RUNNING":
                limit = getattr(q, "max_exec_s", 0.0)
                started = getattr(q, "started_at", None) or q.created_at
                if limit and now - started > limit:
                    self._reap(
                        q,
                        f"Query exceeded maximum execution time limit "
                        f"of {limit:g}s",
                        "execution",
                    )

    def _maybe_gc_journal(self, now: float, force: bool = False):
        """Rate-limited durable-journal GC riding the reaper sweep
        (its thread already exists and already swallows per-sweep
        errors). Terminal entries older than the TTL are dropped and
        counted in ``trino_journal_gc_removed_total``."""
        if not force and now < self._journal_gc_due:
            return
        self._journal_gc_due = now + self.journal_gc_period_s
        journal = getattr(self.coordinator, "journal", None)
        if journal is None:
            return
        removed = journal.gc(self.journal_ttl_s)
        if removed:
            from trino_tpu import telemetry

            telemetry.JOURNAL_GC_REMOVED.inc(removed)

    def _reap(self, q, message: str, reason: str):
        if q.state in ("FINISHED", "FAILED"):
            return
        q.error = f"QueryDeadlineExceededError: {message}"
        q.state = "FAILED"
        q.finished_at = time.time()
        q.cancelled = True
        q.cancel_event.set()
        self.reaped.append((q.query_id, reason))
        # a QUEUED query's dispatch thread is blocked in acquire();
        # poke the condition so it observes cancellation now
        wakeup = getattr(self.coordinator.resource_groups, "wakeup", None)
        if wakeup is not None:
            wakeup()
        # clients long-polling page() must see the reap immediately
        signal = getattr(self.coordinator, "_signal_state", None)
        if signal is not None:
            signal()


class QueryInfoRegistry:
    """Live QueryInfo trees: the registry behind ``GET /v1/query``.

    The analog of the reference coordinator's QueryTracker-as-registry
    role (MAIN/execution/QueryTracker.java holds the QueryInfo every
    UI/API surface reads): runners push per-task operator stats as
    FINISHED task-status responses arrive, so ``GET /v1/query/{id}``
    serves the stage → task → operator tree *while later stages are
    still running*. Finished queries stay visible for a retention
    window (``min.query.expire-age`` analog), then sweep.

    Thread-safe: the coordinator's HTTP threads read while runner
    threads write.
    """

    def __init__(self, retention_s: float = 300.0,
                 max_finished: int = 200):
        self.retention_s = retention_s
        self.max_finished = max_finished
        self._lock = threading.Lock()
        self._entries: dict[str, dict] = {}
        #: query ids inherited from a pre-restart coordinator (via the
        #: durable journal); their rows report recovered=True whether
        #: rehydrated terminal or resumed live — checked by _entry so
        #: the flag survives a begin() racing the recovery thread
        self._recovered_ids: set[str] = set()

    def _entry(self, query_id: str) -> dict:
        e = self._entries.get(query_id)
        if e is None:
            e = self._entries[query_id] = {
                "query_id": query_id,
                "state": "RUNNING",
                "user": None,
                "sql": None,
                "resource_group": None,
                "queued_ms": 0.0,
                "created_at": time.time(),
                "finished_at": None,
                "error": None,
                "rows": None,
                "peak_memory_bytes": 0,
                #: (stage_id, task_id, attempt) -> task row (with
                #: operator_stats); latest attempt wins per task
                "tasks": {},
                #: post-mortem diagnostic bundle (failed queries only)
                "diagnostics": None,
                #: True when this row crossed a coordinator restart
                #: (journal-rehydrated or journal-resumed)
                "recovered": query_id in self._recovered_ids,
            }
        return e

    def begin(self, query_id: str, sql: str | None = None,
              user: str | None = None,
              resource_group: str | None = None,
              queued_ms: float | None = None) -> None:
        if not query_id:
            return
        with self._lock:
            e = self._entry(query_id)
            if sql is not None:
                e["sql"] = sql
            if user is not None:
                e["user"] = user
            if resource_group is not None:
                e["resource_group"] = resource_group
            if queued_ms is not None:
                e["queued_ms"] = float(queued_ms)

    def update_task(self, query_id: str, task_row: dict) -> None:
        if not query_id:
            return
        with self._lock:
            e = self._entry(query_id)
            key = (
                str(task_row.get("stage_id")),
                str(task_row.get("task_id")),
                int(task_row.get("attempt", 0) or 0),
            )
            e["tasks"][key] = task_row
            e["peak_memory_bytes"] = max(
                e["peak_memory_bytes"],
                int(task_row.get("peak_memory_bytes", 0) or 0),
            )

    def finish(self, query_id: str, state: str, rows: int | None = None,
               error: str | None = None,
               peak_memory_bytes: int = 0,
               operator_stats: list | None = None) -> None:
        """Seal a query. ``operator_stats`` covers the local engine,
        whose single-process execution reports one synthetic task."""
        if not query_id:
            return
        with self._lock:
            e = self._entry(query_id)
            e["state"] = state
            e["finished_at"] = time.time()
            e["error"] = error
            if rows is not None:
                e["rows"] = int(rows)
            e["peak_memory_bytes"] = max(
                e["peak_memory_bytes"], int(peak_memory_bytes or 0)
            )
            if operator_stats and not e["tasks"]:
                e["tasks"][("local", "local-0", 0)] = {
                    "stage_id": "local", "task_id": "local-0",
                    "attempt": 0, "state": state, "worker": "local",
                    "operator_stats": operator_stats,
                }
            self._sweep_locked()

    def mark_recovered(self, query_id: str) -> None:
        """Flag a query as crossing a coordinator restart. Safe to
        call before its begin(): the id is remembered and the flag
        applied when the entry materializes."""
        if not query_id:
            return
        with self._lock:
            self._recovered_ids.add(query_id)
            e = self._entries.get(query_id)
            if e is not None:
                e["recovered"] = True

    def rehydrate(self, query_id: str, *, state: str,
                  sql: str | None = None, user: str | None = None,
                  rows: int | None = None, error: str | None = None,
                  elapsed_ms: float = 0.0,
                  diagnostics: dict | None = None) -> None:
        """Restore a terminal query's registry row from its journal
        `done` record after a coordinator restart. The row reports
        recovered=True; task trees are not journaled, so the stage
        list comes back empty (the post-mortem bundle, when present,
        preserves the failure's full context)."""
        if not query_id:
            return
        with self._lock:
            self._recovered_ids.add(query_id)
            e = self._entry(query_id)
            e["recovered"] = True
            e["state"] = state
            e["sql"] = sql if sql is not None else e["sql"]
            e["user"] = user if user is not None else e["user"]
            e["rows"] = int(rows) if rows is not None else e["rows"]
            e["error"] = error
            # reconstruct the timeline the elapsed math expects
            e["finished_at"] = time.time()
            e["created_at"] = e["finished_at"] - (
                float(elapsed_ms or 0.0) / 1e3
            )
            if diagnostics is not None:
                e["diagnostics"] = diagnostics
            self._sweep_locked()

    def set_diagnostics(self, query_id: str, bundle: dict) -> None:
        """Retain a post-mortem bundle; served by
        ``GET /v1/query/{id}/diagnostics`` until the entry sweeps."""
        if not query_id:
            return
        with self._lock:
            self._entry(query_id)["diagnostics"] = bundle

    def get_diagnostics(self, query_id: str) -> dict | None:
        with self._lock:
            e = self._entries.get(query_id)
            return e["diagnostics"] if e else None

    # -- read side ------------------------------------------------------

    def _elapsed_ms(self, e: dict) -> float:
        end = e["finished_at"] or time.time()
        return (end - e["created_at"]) * 1e3

    def list(self) -> list[dict]:
        """Light rows for ``GET /v1/query`` / system.runtime.queries."""
        with self._lock:
            return [
                {
                    "query_id": e["query_id"],
                    "state": e["state"],
                    "user": e["user"],
                    "resource_group": e["resource_group"],
                    "elapsed_ms": round(self._elapsed_ms(e), 3),
                    "queued_time_ms": round(e["queued_ms"], 3),
                    "peak_memory_bytes": e["peak_memory_bytes"],
                    "rows": e["rows"],
                    "error": e["error"],
                    "recovered": bool(e.get("recovered")),
                }
                for e in self._entries.values()
            ]

    def get(self, query_id: str) -> dict | None:
        """Full stage → task → operator tree for one query."""
        from trino_tpu.profiler import tree_from_stats

        with self._lock:
            e = self._entries.get(query_id)
            if e is None:
                return None
            stages: dict[str, dict] = {}
            for (sid, tid, att), row in sorted(e["tasks"].items()):
                st = stages.setdefault(sid, {"stage_id": sid, "tasks": []})
                task = {
                    k: v for k, v in row.items()
                    if k not in ("operator_stats", "query_id", "stage_id")
                }
                task["operators"] = tree_from_stats(
                    row.get("operator_stats") or []
                )
                st["tasks"].append(task)
            return {
                "query_id": e["query_id"],
                "state": e["state"],
                "user": e["user"],
                "sql": e["sql"],
                "elapsed_ms": round(self._elapsed_ms(e), 3),
                "peak_memory_bytes": e["peak_memory_bytes"],
                "rows": e["rows"],
                "error": e["error"],
                "recovered": bool(e.get("recovered")),
                "stages": list(stages.values()),
            }

    def _sweep_locked(self) -> None:
        now = time.time()
        finished = [
            qid for qid, e in self._entries.items()
            if e["finished_at"] is not None
        ]
        for qid in finished:
            e = self._entries[qid]
            if now - e["finished_at"] > self.retention_s:
                del self._entries[qid]
        finished = [
            qid for qid in self._entries
            if self._entries[qid]["finished_at"] is not None
        ]
        while len(finished) > self.max_finished:
            del self._entries[finished.pop(0)]


#: process-wide registry: the coordinator, the fleet runner, and the
#: local engine all live in one coordinator process, so one registry
#: serves every entry point (worker stats arrive via the poll channel)
QUERY_INFO = QueryInfoRegistry()
