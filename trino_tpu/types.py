"""SQL type system.

The analog of the reference's ``io.trino.spi.type`` package (82 files,
SPI/type/): each type knows its device representation (JAX dtype), how
to compare/hash values, and how to render results. Unlike the
reference, a type here maps onto a *fixed-width device array* plus
optional host-side metadata:

- integers / booleans / doubles: the obvious dtypes
- DECIMAL(p, s), p <= 18: scaled int64 (unscaled value), like the
  reference's short decimal (SPI/type/DecimalType.java)
- DATE: int32 days since 1970-01-01 (SPI/type/DateType.java)
- TIMESTAMP: int64 microseconds since epoch
- VARCHAR/CHAR: int32 codes into a *sorted* host-side dictionary
  (lexicographic order preserved, so <, >, ORDER BY work on codes).
  This replaces the reference's pointer-based VariableWidthBlock
  (SPI/block/VariableWidthBlock.java), which has no TPU-friendly form.
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass

import numpy as np

__all__ = [
    "DataType",
    "BooleanType",
    "IntegerKind",
    "DoubleType",
    "RealType",
    "DecimalType",
    "VarcharType",
    "CharType",
    "DateType",
    "TimestampType",
    "UnknownType",
    "MapType",
    "RowType",
    "BOOLEAN",
    "TINYINT",
    "SMALLINT",
    "INTEGER",
    "BIGINT",
    "DOUBLE",
    "REAL",
    "VARCHAR",
    "DATE",
    "TIMESTAMP",
    "UNKNOWN",
    "parse_date",
    "format_date",
    "parse_timestamp",
    "format_timestamp",
    "MICROS_PER_DAY",
]

EPOCH = datetime.date(1970, 1, 1)


def parse_date(s: str) -> int:
    """'1995-03-15' -> days since epoch."""
    y, m, d = s.split("-")
    return (datetime.date(int(y), int(m), int(d)) - EPOCH).days


def format_date(days: int) -> str:
    return (EPOCH + datetime.timedelta(days=int(days))).isoformat()


MICROS_PER_DAY = 86_400_000_000


def parse_timestamp(s: str) -> int:
    """'1995-03-15 12:34:56[.fff]' -> microseconds since epoch."""
    s = s.strip()
    if "T" in s:
        s = s.replace("T", " ")
    if " " in s:
        d, t = s.split(" ", 1)
    else:
        d, t = s, "00:00:00"
    days = parse_date(d)
    parts = t.split(":")
    h = int(parts[0])
    m = int(parts[1]) if len(parts) > 1 else 0
    sec = float(parts[2]) if len(parts) > 2 else 0.0
    return days * MICROS_PER_DAY + (
        (h * 3600 + m * 60) * 1_000_000 + round(sec * 1_000_000)
    )


def format_timestamp(micros: int) -> str:
    micros = int(micros)
    days, rem = divmod(micros, MICROS_PER_DAY)
    secs, us = divmod(rem, 1_000_000)
    h, rest = divmod(secs, 3600)
    m, s = divmod(rest, 60)
    out = f"{format_date(days)} {h:02d}:{m:02d}:{s:02d}"
    if us:
        out += f".{us:06d}".rstrip("0")
    return out


class DataType:
    """Base of all SQL types."""

    name: str = "?"

    #: numpy dtype of the device representation
    np_dtype: np.dtype = np.dtype(np.int64)

    #: True when the device value is an ordinal into a dictionary
    is_dictionary: bool = False

    @property
    def is_numeric(self) -> bool:
        return False

    @property
    def is_integer(self) -> bool:
        return False

    @property
    def is_orderable(self) -> bool:
        return True

    def __repr__(self) -> str:
        return self.name

    def __eq__(self, other) -> bool:
        return (
            type(self) is type(other)
            and repr(self) == repr(other)
            and self.np_dtype == other.np_dtype
        )

    def __hash__(self) -> int:
        return hash(repr(self))


class BooleanType(DataType):
    name = "boolean"
    np_dtype = np.dtype(np.bool_)


@dataclass(frozen=True, repr=False, eq=False)
class IntegerKind(DataType):
    """TINYINT/SMALLINT/INTEGER/BIGINT (SPI/type/BigintType.java etc.)."""

    name: str = "bigint"
    bits: int = 64

    def __post_init__(self):
        object.__setattr__(
            self, "np_dtype", np.dtype(getattr(np, f"int{self.bits}"))
        )

    @property
    def is_numeric(self) -> bool:
        return True

    @property
    def is_integer(self) -> bool:
        return True


class DoubleType(DataType):
    name = "double"
    np_dtype = np.dtype(np.float64)

    @property
    def is_numeric(self) -> bool:
        return True


class RealType(DataType):
    name = "real"
    np_dtype = np.dtype(np.float32)

    @property
    def is_numeric(self) -> bool:
        return True


@dataclass(frozen=True, eq=False, repr=False)
class DecimalType(DataType):
    """Decimal with an unscaled integer representation.

    precision <= 18 ("short"): one int64 per value. precision 19..38
    ("long", the reference's Int128 analog, SPI/spi/type/Int128.java):
    TWO int64 limbs per value — column data has shape [capacity, 2]
    with value = hi * 2^32 + lo (hi signed, lo in [0, 2^32)). Long
    decimals exist primarily as exact aggregate results (sum over
    short-decimal columns); arithmetic stays in the limb domain only
    where implemented (sum/avg/order-by/output).
    """

    precision: int = 18
    scale: int = 0

    np_dtype = np.dtype(np.int64)

    def __post_init__(self):
        if not (0 < self.precision <= 38):
            raise ValueError(f"unsupported decimal precision {self.precision}")
        if not (0 <= self.scale <= self.precision):
            raise ValueError(f"bad decimal scale {self.scale}")

    @property
    def is_long(self) -> bool:
        return self.precision > 18

    @property
    def name(self) -> str:  # type: ignore[override]
        return f"decimal({self.precision},{self.scale})"

    @property
    def is_numeric(self) -> bool:
        return True


@dataclass(frozen=True, eq=False, repr=False)
class VarcharType(DataType):
    """VARCHAR: int32 dictionary codes; strings live host-side.

    Dictionaries are kept lexicographically sorted so that code order ==
    string order; comparisons and ORDER BY run on codes entirely
    on-device. Cross-column string equality/joins remap to a shared
    dictionary on host first (see page.unify_dictionaries).
    """

    length: int | None = None

    np_dtype = np.dtype(np.int32)
    is_dictionary = True

    @property
    def name(self) -> str:  # type: ignore[override]
        if self.length is None:
            return "varchar"
        return f"varchar({self.length})"


@dataclass(frozen=True, eq=False, repr=False)
class CharType(VarcharType):
    @property
    def name(self) -> str:  # type: ignore[override]
        return f"char({self.length})"


class DateType(DataType):
    name = "date"
    np_dtype = np.dtype(np.int32)


class TimestampType(DataType):
    name = "timestamp"
    np_dtype = np.dtype(np.int64)


class UnknownType(DataType):
    """Type of NULL literals before coercion."""

    name = "unknown"
    np_dtype = np.dtype(np.int8)


@dataclass(frozen=True, eq=False, repr=False)
class ArrayType(DataType):
    """ARRAY(element) (SPI/block/ArrayBlock.java analog). Device data
    is an int32 HANDLE lane indexing a host-side ArrayPool holding the
    offsets+values columnar layout (page.ArrayPool) — variable-width
    data stays host-resident with device handles, the same design as
    VARCHAR dictionaries (SURVEY §7 hard parts): per-row descriptors
    gather freely on device while the flat element buffer never
    reorders."""

    element: DataType = None  # type: ignore[assignment]

    np_dtype = np.dtype(np.int32)

    @property
    def name(self) -> str:  # type: ignore[override]
        return f"array({self.element.name})"

    @property
    def is_orderable(self) -> bool:
        return False


@dataclass(frozen=True, eq=False, repr=False)
class MapType(DataType):
    """MAP(key, value) (SPI/type/MapType.java:58 analog). Device data
    is an int32 HANDLE lane indexing a host-side MapPool holding the
    offsets + flat key/value buffers — the same pool+handle design as
    ARRAY (page.MapPool), with two parallel element buffers sharing
    one offsets array."""

    key: DataType = None  # type: ignore[assignment]
    value: DataType = None  # type: ignore[assignment]

    np_dtype = np.dtype(np.int32)

    @property
    def name(self) -> str:  # type: ignore[override]
        return f"map({self.key.name},{self.value.name})"

    @property
    def is_orderable(self) -> bool:
        return False


@dataclass(frozen=True, eq=False, repr=False)
class RowType(DataType):
    """ROW(f1 t1, ...) (SPI/type/RowType.java:67 analog). Device data
    is an int32 HANDLE lane indexing a host-side RowPool holding one
    storage-form column (+ null mask) per field. ``fields`` is a tuple
    of (name | None, DataType); anonymous fields address by 1-based
    ordinal subscript, named fields also by dotted dereference."""

    fields: tuple = ()  # tuple[(str | None, DataType), ...]

    np_dtype = np.dtype(np.int32)

    @property
    def name(self) -> str:  # type: ignore[override]
        parts = [
            (f"{n} {t.name}" if n else t.name) for n, t in self.fields
        ]
        return f"row({','.join(parts)})"

    @property
    def is_orderable(self) -> bool:
        return False

    def field_index(self, name: str) -> int | None:
        for i, (n, _t) in enumerate(self.fields):
            if n is not None and n.lower() == name.lower():
                return i
        return None


@dataclass(frozen=True, eq=False, repr=False)
class SketchType(DataType):
    """Internal multi-lane aggregation state: HLL registers or quantile
    summaries (the analog of the reference's HyperLogLog / QDigest
    state types, SPI/type/ — HLL registers serialized as intermediate
    aggregation state). Column data is [capacity, lanes]; never
    user-visible — it only rides PARTIAL->FINAL exchanges and the
    spooled page serde."""

    kind: str = "hll"  # "hll" (int8 registers) | "quant" (f64 summary)
    lanes: int = 4096

    def __post_init__(self):
        object.__setattr__(
            self, "np_dtype",
            np.dtype(np.int8 if self.kind == "hll" else np.float64),
        )

    @property
    def name(self) -> str:  # type: ignore[override]
        return f"sketch({self.kind},{self.lanes})"

    @property
    def is_orderable(self) -> bool:
        return False


BOOLEAN = BooleanType()
TINYINT = IntegerKind("tinyint", 8)
SMALLINT = IntegerKind("smallint", 16)
INTEGER = IntegerKind("integer", 32)
BIGINT = IntegerKind("bigint", 64)
DOUBLE = DoubleType()
REAL = RealType()
VARCHAR = VarcharType()
DATE = DateType()
TIMESTAMP = TimestampType()
UNKNOWN = UnknownType()

_BY_NAME = {
    "boolean": BOOLEAN,
    "tinyint": TINYINT,
    "smallint": SMALLINT,
    "integer": INTEGER,
    "int": INTEGER,
    "bigint": BIGINT,
    "double": DOUBLE,
    "real": REAL,
    "varchar": VARCHAR,
    "date": DATE,
    "timestamp": TIMESTAMP,
}


def _split_params(inner: str) -> list[str]:
    """Split a type parameter list on top-level commas only —
    map(bigint,array(map(int,int))) nests."""
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(inner):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(inner[start:i])
            start = i + 1
    parts.append(inner[start:])
    return [p.strip() for p in parts]


def type_from_name(name: str) -> DataType:
    base = name.strip().lower()
    if base.startswith("decimal"):
        if "(" not in base:
            return DecimalType(18, 0)
        inner = base[base.index("(") + 1 : base.rindex(")")]
        parts = [int(x) for x in inner.split(",")]
        p = parts[0]
        s = parts[1] if len(parts) > 1 else 0
        return DecimalType(p, s)
    if base.startswith("varchar(") :
        return VarcharType(int(base[8:-1]))
    if base.startswith("sketch("):
        kind, lanes = base[7:-1].split(",")
        return SketchType(kind.strip(), int(lanes))
    if base.startswith("array(") and base.endswith(")"):
        return ArrayType(type_from_name(base[6:-1]))
    if base.startswith("map(") and base.endswith(")"):
        k, v = _split_params(base[4:-1])
        return MapType(type_from_name(k), type_from_name(v))
    if base.startswith("row(") and base.endswith(")"):
        fields = []
        for part in _split_params(base[4:-1]):
            # "name type" or bare "type": a field name is a single
            # identifier token before a space that starts a known type
            if " " in part:
                fn, ft = part.split(" ", 1)
                try:
                    fields.append((fn, type_from_name(ft)))
                    continue
                except ValueError:
                    pass
            fields.append((None, type_from_name(part)))
        return RowType(tuple(fields))
    if base.startswith("char("):
        return CharType(int(base[5:-1]))
    if base in _BY_NAME:
        return _BY_NAME[base]
    raise ValueError(f"unknown type: {name}")


def common_super_type(a: DataType, b: DataType) -> DataType:
    """Least common type for coercion (MAIN/type/TypeCoercion.java analog)."""
    if a == b:
        return a
    if isinstance(a, UnknownType):
        return b
    if isinstance(b, UnknownType):
        return a
    order = {"tinyint": 0, "smallint": 1, "integer": 2, "bigint": 3}
    if a.is_integer and b.is_integer:
        return a if order[a.name] >= order[b.name] else b
    # long (two-limb) decimals coerce through DOUBLE for mixed-type
    # expressions: limb arithmetic exists only where exactness is the
    # contract (sum/avg); everything else takes the numeric-approx path
    # (which also matches the sqlite oracle's REAL behavior)
    if (isinstance(a, DecimalType) and a.is_long and b.is_numeric) or (
        isinstance(b, DecimalType) and b.is_long and a.is_numeric
    ):
        return DOUBLE
    if isinstance(a, DecimalType) and b.is_integer:
        return _decimal_int_super(a)
    if isinstance(b, DecimalType) and a.is_integer:
        return _decimal_int_super(b)
    if isinstance(a, DecimalType) and isinstance(b, DecimalType):
        scale = max(a.scale, b.scale)
        ip = max(a.precision - a.scale, b.precision - b.scale)
        return DecimalType(min(18, ip + scale), scale)
    numeric_to_double = (DoubleType, RealType)
    if isinstance(a, numeric_to_double) and b.is_numeric:
        return DOUBLE if isinstance(a, DoubleType) or isinstance(b, DoubleType) else REAL
    if isinstance(b, numeric_to_double) and a.is_numeric:
        return DOUBLE if isinstance(b, DoubleType) or isinstance(a, DoubleType) else REAL
    if isinstance(a, VarcharType) and isinstance(b, VarcharType):
        return VARCHAR
    if isinstance(a, (DateType, TimestampType)) and isinstance(
        b, (DateType, TimestampType)
    ):
        return TIMESTAMP
    raise TypeError(f"no common type for {a} and {b}")


def _decimal_int_super(d: DecimalType) -> DecimalType:
    # integers widen to decimal(18, s) — bigint is decimal(18,0) here
    # (precision is capped at 18 until int128 lands)
    return DecimalType(18, d.scale)
