"""Distributed execution: device meshes and collective exchanges."""

from trino_tpu.parallel.core import default_mesh, make_mesh

__all__ = ["default_mesh", "make_mesh"]
