"""Device mesh construction.

The analog of the reference's worker-set topology
(MAIN/metadata/DiscoveryNodeManager.java + NodePartitioningManager,
MAIN/sql/planner/NodePartitioningManager.java:59): instead of
discovered HTTP workers, the "cluster" is a jax.sharding.Mesh over the
slice's chips; the partition count is the mesh size and partition->
node mapping is the mesh axis order.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["make_mesh", "default_mesh", "WORKER_AXIS"]

#: the canonical 1-D data-partitioning axis (FIXED_HASH_DISTRIBUTION's
#: partition dimension)
WORKER_AXIS = "workers"


def make_mesh(n_devices: int | None = None, axis: str = WORKER_AXIS) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)}"
            )
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def default_mesh() -> Mesh:
    return make_mesh(None)
