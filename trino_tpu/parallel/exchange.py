"""Hash exchange over the device mesh.

The TPU-native replacement for the reference's shuffle subsystem
(PartitionedOutputOperator -> OutputBuffer -> HTTP long-poll ->
DirectExchangeClient, SURVEY.md §3.4): rows are routed to their owning
device with one ``lax.all_to_all`` over ICI instead of serialize +
HTTP + deserialize. No serde exists at all — device arrays stay device
arrays.

Shapes are static: each shard scatters its rows into ``n`` fixed-size
buckets (one per destination device) and the all_to_all swaps bucket i
of shard j with bucket j of shard i. Bucket overflow is detected and
reported per shard (the analog of output-buffer backpressure; callers
re-run with a bigger bucket or pre-aggregate harder).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["partition_exchange"]


def partition_exchange(
    dest: jnp.ndarray,
    live: jnp.ndarray,
    payload: dict[str, jnp.ndarray],
    n_partitions: int,
    bucket_capacity: int,
    axis: str,
):
    """Route rows to devices by ``dest`` with one all_to_all.

    Must be called inside shard_map over ``axis``. ``dest[i]`` in
    [0, n_partitions) is row i's owning device; dead rows are dropped.

    Returns (received payload dict of [n_partitions * bucket_capacity]
    arrays, received live mask, overflowed: scalar bool — True when a
    bucket was too small and rows were dropped).

    The scatter is the PagePartitioner analog
    (MAIN/operator/output/PagePartitioner.java:134): a rank-per-
    destination prefix sum replaces the per-row appender loop.
    """
    n = dest.shape[0]
    # position of each row within its destination bucket: prefix count
    # of same-destination rows (one-hot cumsum, vectorized appender)
    one_hot = (
        (dest[:, None] == jnp.arange(n_partitions)[None, :]) & live[:, None]
    )
    rank = jnp.cumsum(one_hot.astype(jnp.int32), axis=0) - one_hot.astype(
        jnp.int32
    )
    pos = jnp.take_along_axis(rank, jnp.clip(dest, 0, n_partitions - 1)[:, None], axis=1)[:, 0]
    counts = jnp.sum(one_hot, axis=0)
    overflowed = jnp.any(counts > bucket_capacity)

    in_range = live & (pos < bucket_capacity)
    flat_idx = jnp.where(
        in_range, dest * bucket_capacity + pos, n_partitions * bucket_capacity
    )

    out = {}
    for name, arr in payload.items():
        trailing = arr.shape[1:]  # two-limb decimal columns are [n, 2]
        buckets = jnp.zeros(
            (n_partitions * bucket_capacity,) + trailing, dtype=arr.dtype
        ).at[flat_idx].set(arr, mode="drop")
        buckets = buckets.reshape(
            (n_partitions, bucket_capacity) + trailing
        )
        # swap bucket p of this shard with bucket <this> of shard p
        received = jax.lax.all_to_all(
            buckets, axis, split_axis=0, concat_axis=0, tiled=False
        )
        out[name] = received.reshape((-1,) + trailing)
    sent_live = jnp.zeros(
        (n_partitions * bucket_capacity,), dtype=jnp.bool_
    ).at[flat_idx].set(True, mode="drop")
    sent_live = sent_live.reshape(n_partitions, bucket_capacity)
    recv_live = jax.lax.all_to_all(
        sent_live, axis, split_axis=0, concat_axis=0, tiled=False
    ).reshape(-1)
    return out, recv_live, overflowed
