"""Distributed hash aggregation over the mesh.

The TPU-native form of the reference's two-step distributed group-by
(partial HashAggregationOperator -> hash exchange -> final
HashAggregationOperator; step split planned by AddExchanges,
MAIN/sql/planner/optimizations/AddExchanges.java:142):

1. each shard partial-aggregates its rows into a local slot table
   (``assign_groups`` + segment sums) — the PARTIAL step;
2. surviving (key, partial-state) rows are routed to the device that
   owns their hash — ``partition_exchange`` (one all_to_all on ICI);
3. the owner runs the same slot assignment over received rows and
   combines partial states — the FINAL step.

The whole thing is one jitted SPMD program under shard_map: XLA sees
the partial reduction, the collective, and the final reduction as one
fusion region per shard.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from trino_tpu.exec import kernels as K
from trino_tpu.parallel.exchange import partition_exchange

__all__ = ["distributed_group_sums", "make_group_sums_step"]


def _local_partial(key_bits, key_null, vals, live, capacity):
    """Shard-local partial aggregation: slot table + per-slot sums.

    Returns an ``overflow`` flag: live rows that ``assign_groups``
    could not place (group == capacity) would otherwise be routed into
    the drop slot and silently vanish — callers must retry with a
    larger capacity when it trips."""
    group, owner = K.assign_groups((key_bits,), (key_null,), live, capacity)
    overflow = jnp.any(live & (group == capacity))
    g = jnp.where(live, group, capacity)
    sums = [K.seg_sum(jnp.where(live, v, 0), g, capacity) for v in vals]
    counts = K.seg_sum(live.astype(jnp.int64), g, capacity)
    n = live.shape[0]
    own = jnp.clip(owner, 0, n - 1)
    slot_key = key_bits[own]
    slot_null = key_null[own]
    slot_live = owner < n
    return slot_key, slot_null, sums, counts, slot_live, overflow


def make_group_sums_step(
    mesh: Mesh,
    axis: str,
    n_values: int,
    local_capacity: int,
    final_capacity: int,
    bucket_capacity: int,
):
    """Build the jitted SPMD step.

    Input arrays are sharded [n_devices * rows_per_shard] along
    ``axis``; outputs are per-device final slot tables:
    (key_bits, key_null, sums..., counts, slot_live), each
    [n_devices * final_capacity] sharded along ``axis``.
    """
    n_part = mesh.shape[axis]

    def step(key_bits, key_null, live, *vals):
        # PARTIAL: local slot table
        sk, sn, sums, counts, slive, part_ovf = _local_partial(
            key_bits, key_null, list(vals), live, local_capacity
        )
        # route each surviving group to its owning device by key hash
        h = K.hash_columns([(sk, None), (sn.astype(jnp.uint64), None)])
        dest = (h % jnp.uint64(n_part)).astype(jnp.int32)
        payload = {"k": sk, "n": sn.astype(jnp.int8), "c": counts}
        for i, s in enumerate(sums):
            payload[f"v{i}"] = s
        recv, rlive, overflow = partition_exchange(
            dest, slive, payload, n_part, bucket_capacity, axis
        )
        # FINAL: combine partial states per key on the owner
        rk = recv["k"]
        rn = recv["n"].astype(jnp.bool_)
        group, owner = K.assign_groups(
            (rk,), (rn,), rlive, final_capacity
        )
        final_ovf = jnp.any(rlive & (group == final_capacity))
        g = jnp.where(rlive, group, final_capacity)
        fsums = [
            K.seg_sum(jnp.where(rlive, recv[f"v{i}"], 0), g, final_capacity)
            for i in range(n_values)
        ]
        fcount = K.seg_sum(
            jnp.where(rlive, recv["c"], 0), g, final_capacity
        )
        nr = rlive.shape[0]
        own = jnp.clip(owner, 0, nr - 1)
        out_key = rk[own]
        out_null = rn[own]
        out_live = owner < nr
        # overflow covers exchange-bucket AND slot-table overflow on any
        # shard; reduce so the replicated output is sound
        overflow = overflow | part_ovf | final_ovf
        overflow = jax.lax.pmax(overflow.astype(jnp.int32), axis) > 0
        return (out_key, out_null, *fsums, fcount, out_live, overflow)

    sharded = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis)) + (P(axis),) * n_values,
        out_specs=(P(axis), P(axis))
        + (P(axis),) * n_values
        + (P(axis), P(axis), P()),
        # while_loop carries start as unvarying constants inside the
        # per-shard program; skip the varying-manual-axes typecheck
        check_vma=False,
    )
    return jax.jit(sharded)


def distributed_group_sums(
    mesh: Mesh,
    axis: str,
    key_bits: jnp.ndarray,
    key_null: jnp.ndarray,
    live: jnp.ndarray,
    vals: list[jnp.ndarray],
    local_capacity: int,
    final_capacity: int,
    bucket_capacity: int | None = None,
):
    """Group-by-key sums + counts across the mesh (convenience wrapper).

    Inputs are global [N] arrays; they are sharded along ``axis``
    (N must divide by the mesh size). Returns host-inspectable
    (key_bits, key_null, sums, counts, slot_live, overflowed) where
    the slot arrays are [n_devices * final_capacity].
    """
    n_part = mesh.shape[axis]
    if bucket_capacity is None:
        bucket_capacity = local_capacity  # safe: <= local groups total
    step = make_group_sums_step(
        mesh, axis, len(vals), local_capacity, final_capacity, bucket_capacity
    )
    sharding = NamedSharding(mesh, P(axis))
    args = [
        jax.device_put(a, sharding)
        for a in (key_bits, key_null, live, *vals)
    ]
    out = step(*args)
    *head, overflow = out
    key, null, *sums_count = head
    sums = sums_count[: len(vals)]
    counts, slot_live = sums_count[len(vals)], sums_count[len(vals) + 1]
    return key, null, sums, counts, slot_live, bool(overflow)
