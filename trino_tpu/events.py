"""Event listener SPI: query lifecycle events for observability.

The analog of the reference's EventListener SPI
(SPI/eventlistener/EventListener.java + QueryCompletedEvent.java):
pluggable listeners registered on the Metadata receive a
QueryCompletedEvent after every statement — success or failure — with
identity, timing, and io counters. Listeners must not fail the query:
exceptions are swallowed (the reference isolates listener errors the
same way), but each swallow is counted in the metrics registry and
logged at debug level so a broken listener is visible.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import time
from dataclasses import dataclass, field

from trino_tpu import telemetry

__all__ = [
    "QueryCompletedEvent",
    "EventListener",
    "StructuredLogListener",
    "fire_query_completed",
    "fire_slow_query",
    "maybe_log_slow_query",
]

_log = logging.getLogger("trino_tpu.events")


@dataclass(frozen=True)
class QueryCompletedEvent:
    """One finished statement (QueryCompletedEvent analog)."""

    query_id: str
    user: str
    sql: str
    #: FINISHED | FAILED
    state: str
    elapsed_ms: float
    #: result rows returned (0 for DDL/DML acks)
    rows: int
    #: error text when state == FAILED
    error: str | None = None
    #: wall-clock seconds since epoch at completion
    end_time: float = field(default_factory=time.time)
    #: peak concurrent memory reservation (QueryStatistics
    #: peakUserMemoryBytes analog); 0 when the statement reserved
    #: nothing (DDL, SHOW, ...)
    peak_memory_bytes: int = 0
    #: per-node attribution as ((node_id, bytes), ...) — a tuple
    #: because the event is frozen/hashable
    peak_memory_per_node: tuple = ()
    #: elapsed split (QueryStatistics queued/planning/execution/cpu
    #: analog); queued_ms is only nonzero for coordinator-submitted
    #: queries that waited for admission
    queued_ms: float = 0.0
    planning_ms: float = 0.0
    execution_ms: float = 0.0
    cpu_ms: float = 0.0
    #: FTE / governance counters (mirrors of the QueryResult fields)
    query_retries: int = 0
    tasks_retried: int = 0
    tasks_speculated: int = 0
    speculation_wins: int = 0
    workers_readmitted: int = 0
    #: performance-sentry identity: the journal plan digest + session
    #: property fingerprint keying this statement's baseline (None for
    #: unplannable/errored statements)
    plan_digest: str | None = None
    session_fingerprint: str | None = None
    #: which cache tier served the result ("result" / "hbm" / None)
    cache_hit_tier: str | None = None
    #: real backend compiles attributed to this statement
    compiles: int = 0
    #: worst exchange partition max/mean ratio across stages (1.0 =
    #: perfectly balanced; 0.0 = no exchanges)
    exchange_skew: float = 0.0
    #: heavy diagnostic context — excluded from eq/hash (the frozen
    #: event stays hashable) and dropped by StructuredLogListener;
    #: carried so the sentry can bundle an anomalous SUCCESS with the
    #: same evidence a failure gets
    time_breakdown: dict | None = field(default=None, compare=False)
    plan_text: str | None = field(default=None, compare=False)
    trace: object = field(default=None, compare=False)
    task_stats: tuple = field(default=(), compare=False)


class EventListener:
    """SPI base: override any subset."""

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass

    def slow_query(self, record: dict) -> None:
        """One query crossed ``slow_query_log_threshold``; ``record``
        is the profile summary (top operators by self time)."""
        pass


class StructuredLogListener(EventListener):
    """Writes one JSON line per completed query — the reference's
    http-event-listener / query-log analog, pointed at a local file
    or any writable stream."""

    def __init__(self, path: str | None = None, stream=None) -> None:
        if (path is None) == (stream is None):
            raise ValueError("pass exactly one of path= or stream=")
        self._path = path
        self._stream = stream

    def query_completed(self, event: QueryCompletedEvent) -> None:
        # drop the heavy diagnostic payloads BEFORE asdict: the trace
        # is a live span tree (deep-copying it is wrong and expensive)
        # and the query log is a summary stream, not a bundle store
        slim = dataclasses.replace(
            event, trace=None, task_stats=(), plan_text=None,
        )
        rec = dataclasses.asdict(slim)
        rec.pop("trace", None)
        rec.pop("task_stats", None)
        rec.pop("plan_text", None)
        rec["peak_memory_per_node"] = [
            list(kv) for kv in event.peak_memory_per_node
        ]
        self._write(rec)

    def slow_query(self, record: dict) -> None:
        self._write(record)

    def _write(self, rec: dict) -> None:
        line = json.dumps(rec, sort_keys=True, default=str)
        if self._path is not None:
            with open(self._path, "a") as f:
                f.write(line + "\n")
        else:
            self._stream.write(line + "\n")


def fire_slow_query(listeners, record: dict) -> None:
    """Deliver one slow-query record, isolating listener failures the
    same way as ``fire_query_completed``."""
    for lst in listeners:
        try:
            lst.slow_query(record)
        except Exception:
            telemetry.LISTENER_FAILURES.inc(listener=type(lst).__name__)
            _log.debug(
                "event listener %s raised in slow_query for %s",
                type(lst).__name__, record.get("query_id"),
                exc_info=True,
            )


def maybe_log_slow_query(
    listeners, session, query_id: str, sql: str, elapsed_ms: float,
    operator_stats: list | None, state: str = "FINISHED",
    time_breakdown: dict | None = None,
    kernel_profile: dict | None = None,
) -> None:
    """Fire one structured slow-query record when the statement ran
    past the ``slow_query_log_threshold`` session property (0 = off).
    The record is a profile *summary* — the top-3 operators by self
    time plus the wall-clock bucket decomposition — not the full tree;
    ``GET /v1/query/{id}`` and ``profile_json()`` serve the rest."""
    if not listeners:
        return
    from trino_tpu import session_properties as SP

    try:
        threshold_s = SP.parse_duration(
            SP.get(session, "slow_query_log_threshold")
        )
    except Exception:
        return
    if threshold_s <= 0 or elapsed_ms < threshold_s * 1e3:
        return
    top = sorted(
        operator_stats or [],
        key=lambda r: r.get("self_ms", 0.0), reverse=True,
    )[:3]
    fire_slow_query(listeners, {
        "event": "slow_query",
        "query_id": query_id,
        "user": getattr(session, "user", None),
        "sql": sql,
        "state": state,
        "elapsed_ms": round(elapsed_ms, 3),
        "threshold": f"{threshold_s:g}s",
        "operators": len(operator_stats or []),
        "top_operators": [
            {
                k: r.get(k)
                for k in (
                    "name", "node_type", "self_ms", "wall_ms",
                    "rows_out", "achieved_gflops",
                    "roofline_utilization",
                )
                if k in r
            }
            for r in top
        ],
        **(
            {"time_breakdown": time_breakdown.get("buckets")}
            if time_breakdown else {}
        ),
        # per-HLO-scope device attribution, present when the session
        # ran with kernel_profile=AUTO/ON (kernel observatory)
        **(
            {"kernel_profile": kernel_profile}
            if kernel_profile else {}
        ),
    })


def fire_query_completed(listeners, event: QueryCompletedEvent) -> None:
    """Deliver to every listener, isolating failures (a broken
    listener must never fail the query — reference behavior). Each
    swallowed exception increments
    ``trino_event_listener_failures_total`` and is debug-logged."""
    for lst in listeners:
        try:
            lst.query_completed(event)
        except Exception:
            telemetry.LISTENER_FAILURES.inc(
                listener=type(lst).__name__
            )
            _log.debug(
                "event listener %s raised in query_completed for %s",
                type(lst).__name__, event.query_id, exc_info=True,
            )
