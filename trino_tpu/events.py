"""Event listener SPI: query lifecycle events for observability.

The analog of the reference's EventListener SPI
(SPI/eventlistener/EventListener.java + QueryCompletedEvent.java):
pluggable listeners registered on the Metadata receive a
QueryCompletedEvent after every statement — success or failure — with
identity, timing, and io counters. Listeners must not fail the query:
exceptions are swallowed (the reference isolates listener errors the
same way).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

__all__ = ["QueryCompletedEvent", "EventListener", "fire_query_completed"]


@dataclass(frozen=True)
class QueryCompletedEvent:
    """One finished statement (QueryCompletedEvent analog)."""

    query_id: str
    user: str
    sql: str
    #: FINISHED | FAILED
    state: str
    elapsed_ms: float
    #: result rows returned (0 for DDL/DML acks)
    rows: int
    #: error text when state == FAILED
    error: str | None = None
    #: wall-clock seconds since epoch at completion
    end_time: float = field(default_factory=time.time)
    #: peak concurrent memory reservation (QueryStatistics
    #: peakUserMemoryBytes analog); 0 when the statement reserved
    #: nothing (DDL, SHOW, ...)
    peak_memory_bytes: int = 0
    #: per-node attribution as ((node_id, bytes), ...) — a tuple
    #: because the event is frozen/hashable
    peak_memory_per_node: tuple = ()


class EventListener:
    """SPI base: override any subset."""

    def query_completed(self, event: QueryCompletedEvent) -> None:
        pass


def fire_query_completed(listeners, event: QueryCompletedEvent) -> None:
    """Deliver to every listener, isolating failures (a broken
    listener must never fail the query — reference behavior)."""
    for lst in listeners:
        try:
            lst.query_completed(event)
        except Exception:
            pass
