from trino_tpu.expr.ir import (
    AggCall,
    Call,
    Cast,
    InputRef,
    Literal,
    RowExpression,
)
from trino_tpu.expr.compiler import compile_expr, ColumnLayout

__all__ = [
    "AggCall",
    "Call",
    "Cast",
    "InputRef",
    "Literal",
    "RowExpression",
    "compile_expr",
    "ColumnLayout",
]
