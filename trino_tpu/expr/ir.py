"""Typed row-expression IR.

The analog of the reference's ``RowExpression`` tree
(MAIN/sql/relational/RowExpression.java: ConstantExpression,
InputReferenceExpression, CallExpression, SpecialForm). The analyzer
produces *typed* nodes with explicit ``Cast``s inserted, so the
compiler is a straightforward (function, argument types) -> kernel
dispatch with no implicit coercion.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from trino_tpu import types as T

__all__ = ["RowExpression", "Literal", "InputRef", "Call", "Cast", "AggCall"]


@dataclass(frozen=True)
class RowExpression:
    type: T.DataType


@dataclass(frozen=True)
class Literal(RowExpression):
    value: Any = None  # python value; None = SQL NULL

    def __repr__(self):
        return f"lit({self.value!r}:{self.type})"


@dataclass(frozen=True)
class InputRef(RowExpression):
    name: str = ""

    def __repr__(self):
        return f"{self.name}:{self.type}"


@dataclass(frozen=True)
class Call(RowExpression):
    """Scalar function or operator call.

    Function names are lowercase: arithmetic ("add", "subtract",
    "multiply", "divide", "modulus", "negate"), comparison ("eq", "ne",
    "lt", "le", "gt", "ge"), logic ("and", "or", "not"), special forms
    ("if", "case", "coalesce", "in", "between", "is_null", "like"),
    and the scalar library ("extract_year", "substr", ...).
    """

    name: str = ""
    args: tuple[RowExpression, ...] = ()

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.args))})"


@dataclass(frozen=True)
class Cast(RowExpression):
    arg: RowExpression = None  # type: ignore[assignment]

    def __repr__(self):
        return f"cast({self.arg!r} as {self.type})"


@dataclass(frozen=True)
class AggCall:
    """Aggregate function reference used by Aggregate plan nodes
    (analog of MAIN/sql/planner/plan/AggregationNode.Aggregation)."""

    name: str  # sum/count/avg/min/max/count_all/...
    args: tuple[RowExpression, ...]
    type: T.DataType
    distinct: bool = False
    filter: RowExpression | None = None

    def __repr__(self):
        d = "distinct " if self.distinct else ""
        return f"{self.name}({d}{', '.join(map(repr, self.args))})"
