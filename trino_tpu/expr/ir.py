"""Typed row-expression IR.

The analog of the reference's ``RowExpression`` tree
(MAIN/sql/relational/RowExpression.java: ConstantExpression,
InputReferenceExpression, CallExpression, SpecialForm). The analyzer
produces *typed* nodes with explicit ``Cast``s inserted, so the
compiler is a straightforward (function, argument types) -> kernel
dispatch with no implicit coercion.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from trino_tpu import types as T

__all__ = [
    "RowExpression", "Literal", "InputRef", "Call", "Cast", "AggCall",
    "join_key_compatible",
]


def join_key_compatible(a: T.DataType, b: T.DataType) -> bool:
    """True when symbol-equality on these types may become a raw-bits
    join/group key (executor compares unscaled device values).

    Mixed-scale decimals store the same value as different ints, and
    float32/float64 have different bit layouts — those must stay as
    compiled comparisons, not hash-join criteria."""
    if isinstance(a, T.DecimalType) or isinstance(b, T.DecimalType):
        return (
            isinstance(a, T.DecimalType)
            and isinstance(b, T.DecimalType)
            and a.scale == b.scale
        )
    if a.np_dtype.kind == "f" or b.np_dtype.kind == "f":
        return a.np_dtype == b.np_dtype
    return True


@dataclass(frozen=True)
class RowExpression:
    type: T.DataType


@dataclass(frozen=True)
class Literal(RowExpression):
    value: Any = None  # python value; None = SQL NULL

    def __repr__(self):
        return f"lit({self.value!r}:{self.type})"


@dataclass(frozen=True)
class InputRef(RowExpression):
    name: str = ""

    def __repr__(self):
        return f"{self.name}:{self.type}"


@dataclass(frozen=True)
class Call(RowExpression):
    """Scalar function or operator call.

    Function names are lowercase: arithmetic ("add", "subtract",
    "multiply", "divide", "modulus", "negate"), comparison ("eq", "ne",
    "lt", "le", "gt", "ge"), logic ("and", "or", "not"), special forms
    ("if", "case", "coalesce", "in", "between", "is_null", "like"),
    and the scalar library ("extract_year", "substr", ...).
    """

    name: str = ""
    args: tuple[RowExpression, ...] = ()

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.args))})"


@dataclass(frozen=True)
class Cast(RowExpression):
    arg: RowExpression = None  # type: ignore[assignment]

    def __repr__(self):
        return f"cast({self.arg!r} as {self.type})"


@dataclass(frozen=True)
class AggCall:
    """Aggregate function reference used by Aggregate plan nodes
    (analog of MAIN/sql/planner/plan/AggregationNode.Aggregation)."""

    name: str  # sum/count/avg/min/max/count_all/...
    args: tuple[RowExpression, ...]
    type: T.DataType
    distinct: bool = False
    filter: RowExpression | None = None

    def __repr__(self):
        d = "distinct " if self.distinct else ""
        return f"{self.name}({d}{', '.join(map(repr, self.args))})"
