"""Expression -> jittable column-function compiler.

The analog of the reference's runtime bytecode generation
(MAIN/sql/gen/ExpressionCompiler.java:56, PageFunctionCompiler.java:102):
instead of emitting JVM bytecode per query, we trace typed
RowExpressions into closures over jax.numpy ops. The closure evaluates
a whole column at once; XLA fuses the resulting elementwise graph into
the surrounding kernel.

Null semantics: every evaluation returns ``(data, valid)`` where
``valid`` is a boolean array or None (all valid). Logic ops implement
SQL three-valued (Kleene) truth tables.

Strings: device data is dictionary codes. String-content functions
(LIKE, substr, lower, ...) are evaluated *over the dictionary values on
host at compile time* — a LIKE becomes a boolean lookup table indexed
by code, a substr becomes a code-remap gather. Each compiles to O(dict)
host work once plus an O(n) device gather, replacing per-row string
processing entirely (the dictionary-encode-early strategy from
SURVEY.md §7).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.expr.ir import Call, Cast, InputRef, Literal, RowExpression
from trino_tpu.page import StringDictionary

__all__ = ["ColumnLayout", "CompiledExpr", "compile_expr"]

# evaluation environment: name -> (data, valid|None)
Env = dict[str, tuple[jnp.ndarray, jnp.ndarray | None]]


@dataclass
class ColumnLayout:
    """Input layout a compilation binds to: types + dictionaries.

    The cache key role of (expression, input layout) mirrors
    PageFunctionCompiler's cache keyed on RowExpression + channels.
    """

    types: dict[str, T.DataType] = field(default_factory=dict)
    dictionaries: dict[str, StringDictionary | None] = field(default_factory=dict)
    #: host ArrayPools of ARRAY-typed input columns (page.ArrayPool);
    #: array functions compile host LUTs over the pool and gather by
    #: the device handle lanes
    array_pools: dict = field(default_factory=dict)


@dataclass
class CompiledExpr:
    fn: Callable[[Env], tuple[jnp.ndarray, jnp.ndarray | None]]
    type: T.DataType
    dictionary: StringDictionary | None = None  # set when type is varchar
    is_literal: bool = False
    #: set when the result is a pool-backed handle lane (map_keys /
    #: map_values emit a derived ArrayPool over the map pool's buffers)
    pool: object | None = None


def compile_expr(expr: RowExpression, layout: ColumnLayout) -> CompiledExpr:
    return _Compiler(layout).compile(expr)


def _and_valid(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


class _Compiler:
    def __init__(self, layout: ColumnLayout):
        self.layout = layout

    def compile(self, expr: RowExpression) -> CompiledExpr:
        if isinstance(expr, Literal):
            return self._literal(expr)
        if isinstance(expr, InputRef):
            name = expr.name
            return CompiledExpr(
                lambda env: env[name],
                expr.type,
                self.layout.dictionaries.get(name),
            )
        if isinstance(expr, Cast):
            return self._cast(expr)
        if isinstance(expr, Call):
            return self._call(expr)
        raise NotImplementedError(f"cannot compile {expr!r}")

    def _array_fn(self, expr: Call) -> CompiledExpr:
        """Array functions over pool-backed columns: a host LUT sized
        by the pool (lengths / element-at-k / contains-constant) plus
        one device gather by the handle lane — the same compile-time
        shape as dictionary string predicates (the ArrayBlock ops of
        the reference lowered to the pool+handle design)."""
        name = expr.name
        arr = expr.args[0]
        from trino_tpu.page import ArrayPool, MapPool, RowPool

        if isinstance(arr, InputRef):
            pool = self.layout.array_pools.get(arr.name)
            if pool is None:
                raise NotImplementedError(
                    f"{name}: column {arr.name!r} has no array pool"
                )
            a = self.compile(arr)
        elif isinstance(arr, Literal) and arr.value is not None:
            # constant ARRAY[]/MAP()/ROW() literal: _literal builds the
            # one-entry pool + constant handle 0
            a = self.compile(arr)
            pool = a.pool
        else:
            raise NotImplementedError(
                f"{name} over a computed array expression"
            )
        n = max(len(pool), 1)

        if isinstance(pool, RowPool) or name == "row_field":
            return self._row_field(expr, a, pool, n)
        if isinstance(pool, MapPool):
            if name in ("map_keys", "map_values"):
                # a derived ArrayPool sharing the map pool's offsets
                # and one of its flat buffers; handles pass through
                buf = pool.keys if name == "map_keys" else pool.values
                et = (
                    pool.key_type if name == "map_keys"
                    else pool.value_type
                )
                derived = ArrayPool(pool.offsets, buf, et)
                return CompiledExpr(
                    a.fn, T.ArrayType(et), pool=derived
                )
            if name == "subscript":
                return self._map_subscript(expr, a, pool, n)
            # cardinality falls through to the shared lengths path
        lens = pool.lengths()
        if name == "cardinality":
            table = jnp.asarray(
                np.pad(lens, (0, n - len(lens))).astype(np.int64)
            )

            def ev_card(env):
                h, v = a.fn(env)
                return table[jnp.clip(h, 0, n - 1)], v

            return CompiledExpr(ev_card, T.BIGINT)
        if name == "subscript":
            idx = expr.args[1]
            if not isinstance(idx, Literal) or idx.value is None:
                raise NotImplementedError(
                    "array subscript index must be a constant"
                )
            k = int(idx.value)
            ok_h = (lens >= k) & (k >= 1)
            at = np.where(ok_h, pool.offsets[:-1] + (k - 1), 0)
            vals = pool.values[np.clip(at, 0, max(len(pool.values) - 1, 0))] \
                if len(pool.values) else np.zeros(len(lens), dtype=np.int64)
            et = expr.type
            out_dict = None
            if isinstance(et, T.VarcharType):
                out_dict, codes = StringDictionary.from_strings(
                    vals.astype(str) if len(vals) else np.asarray([], str)
                )
                vals = codes
            tbl = jnp.asarray(np.pad(
                np.asarray(vals, dtype=et.np_dtype), (0, n - len(lens))
            ))
            okt = jnp.asarray(np.pad(ok_h, (0, n - len(lens))))

            def ev_sub(env):
                h, v = a.fn(env)
                hc = jnp.clip(h, 0, n - 1)
                ok = okt[hc] if v is None else (okt[hc] & v)
                return tbl[hc], ok

            return CompiledExpr(ev_sub, et, out_dict)
        # contains(arr, constant)
        needle = expr.args[1]
        if not isinstance(needle, Literal) or needle.value is None:
            raise NotImplementedError(
                "contains() needle must be a constant"
            )
        want = _literal_device_value(needle)
        if len(pool.values) and len(lens):
            # vectorized segmented any: one equality pass + scatter-or
            # by array id (reduceat would mis-segment when trailing
            # arrays are empty: offsets[:-1] may equal len(values))
            eq = pool.values == want
            seg_id = np.repeat(np.arange(len(lens)), lens)
            hit = np.zeros(len(lens), dtype=np.bool_)
            np.logical_or.at(hit, seg_id, eq)
        else:
            hit = np.zeros(len(lens), dtype=np.bool_)
        ht = jnp.asarray(np.pad(hit, (0, n - len(lens))))

        def ev_contains(env):
            h, v = a.fn(env)
            return ht[jnp.clip(h, 0, n - 1)], v

        return CompiledExpr(ev_contains, T.BOOLEAN)

    def _map_subscript(self, expr: Call, a, pool, n: int) -> CompiledExpr:
        """map[key] / element_at(map, key) with a constant key: a host
        LUT (value-at-key per map, presence mask) + one device gather
        (MapSubscriptOperator / MapElementAt lowered to pool+handle;
        absent keys yield NULL)."""
        key = expr.args[1]
        if not isinstance(key, Literal) or key.value is None:
            raise NotImplementedError("map key must be a constant")
        want = _literal_device_value(key)
        lens = pool.lengths()
        m = len(lens)
        if len(pool.keys) and m:
            eq = pool.keys == want
            # scatter-min by map id (reduceat would mis-segment when
            # trailing maps are empty: offsets[:-1] may equal len(keys))
            map_id = np.repeat(np.arange(m), lens)
            pos = np.where(eq, np.arange(len(eq)), len(eq))
            first = np.full(m, len(eq), dtype=np.int64)
            np.minimum.at(first, map_id, pos)
            ok_h = first < len(eq)
            at = np.where(ok_h, first, 0)
            vals = pool.values[at]
        else:
            ok_h = np.zeros(m, dtype=np.bool_)
            vals = np.zeros(m, dtype=np.int64)
        et = expr.type
        out_dict = None
        if vals.dtype == object:
            # NULL map values ride object buffers: clear validity and
            # fill with the type's zero so the fixed-width cast succeeds
            nn = np.asarray([v is not None for v in vals], dtype=np.bool_)
            ok_h = ok_h & nn
            fill = "" if isinstance(et, T.VarcharType) else 0
            vals = np.asarray(
                [fill if v is None else v for v in vals], dtype=object
            )
        if isinstance(et, T.VarcharType):
            out_dict, codes = StringDictionary.from_strings(
                vals.astype(str) if len(vals) else np.asarray([], str)
            )
            vals = codes
        tbl = jnp.asarray(np.pad(
            np.asarray(vals, dtype=et.np_dtype), (0, n - m)
        ))
        okt = jnp.asarray(np.pad(ok_h, (0, n - m)))

        def ev(env):
            h, v = a.fn(env)
            hc = jnp.clip(h, 0, n - 1)
            ok = okt[hc] if v is None else (okt[hc] & v)
            return tbl[hc], ok

        return CompiledExpr(ev, et, out_dict)

    def _row_field(self, expr: Call, a, pool, n: int) -> CompiledExpr:
        """row[ordinal] / row.name: the field's pool column is itself
        the LUT — one device gather by handle (RowBlock field access)."""
        idx = expr.args[1]
        if not isinstance(idx, Literal) or idx.value is None:
            raise NotImplementedError("row field index must be constant")
        fi = int(idx.value)
        vals, fvalid = pool.fields[fi]
        et = expr.type
        out_dict = None
        if isinstance(et, T.VarcharType):
            out_dict, codes = StringDictionary.from_strings(
                vals.astype(str) if len(vals) else np.asarray([], str)
            )
            vals = codes
        m = len(vals)
        tbl = jnp.asarray(np.pad(
            np.asarray(vals, dtype=et.np_dtype), (0, n - m)
        ))
        okt = None
        if fvalid is not None:
            okt = jnp.asarray(np.pad(fvalid, (0, n - m)))

        def ev(env):
            h, v = a.fn(env)
            hc = jnp.clip(h, 0, n - 1)
            ok = v
            if okt is not None:
                ok = okt[hc] if v is None else (okt[hc] & v)
            return tbl[hc], ok

        return CompiledExpr(ev, et, out_dict)

    # ---- literals --------------------------------------------------------
    def _literal(self, expr: Literal) -> CompiledExpr:
        if expr.value is None:
            dtype = expr.type.np_dtype
            return CompiledExpr(
                lambda env: (
                    jnp.zeros((), dtype=dtype),
                    jnp.zeros((), dtype=jnp.bool_),
                ),
                expr.type,
                is_literal=True,
            )
        if isinstance(expr.type, (T.ArrayType, T.MapType, T.RowType)):
            # a one-entry pool + constant handle 0 (the ValuesNode
            # single-row constant form of pool-backed columns)
            from trino_tpu.page import ArrayPool, MapPool, RowPool

            t = expr.type
            if isinstance(t, T.MapType):
                pool, _h = MapPool.from_pymaps(
                    [list(expr.value)], t.key, t.value
                )
            elif isinstance(t, T.RowType):
                pool, _h = RowPool.from_pytuples([expr.value], t)
            else:
                pool, _h = ArrayPool.from_pylists(
                    [list(expr.value)], t.element
                )
            return CompiledExpr(
                lambda env: (jnp.zeros((), dtype=jnp.int32), None),
                t, is_literal=True, pool=pool,
            )
        if isinstance(expr.type, T.VarcharType):
            d = StringDictionary(np.asarray([str(expr.value)]))
            return CompiledExpr(
                lambda env: (jnp.zeros((), dtype=jnp.int32), None),
                expr.type,
                d,
                is_literal=True,
            )
        value = _literal_device_value(expr)
        dtype = expr.type.np_dtype
        return CompiledExpr(
            lambda env: (jnp.asarray(value, dtype=dtype), None),
            expr.type,
            is_literal=True,
        )

    # ---- casts -----------------------------------------------------------
    def _cast(self, expr: Cast) -> CompiledExpr:
        src = self.compile(expr.arg)
        s_t, d_t = src.type, expr.type
        if s_t == d_t:
            return src

        def wrap(f):
            def ev(env):
                data, valid = src.fn(env)
                return f(data), valid

            # a cast of a literal is still a literal (NULL literals in
            # CASE branches arrive here wrapped in a coercion Cast)
            return CompiledExpr(ev, d_t, is_literal=src.is_literal)

        if isinstance(d_t, T.DoubleType) or isinstance(d_t, T.RealType):
            dtype = d_t.np_dtype
            if isinstance(s_t, T.DecimalType) and s_t.is_long:
                # two-limb -> double: hi*2^32 + lo, then unscale
                # (float64 approximation; exactness lives in the limb
                # aggregates, not in mixed arithmetic)
                scale = 10.0 ** s_t.scale
                return wrap(
                    lambda x: (
                        x[..., 0].astype(jnp.float64) * 4294967296.0
                        + x[..., 1].astype(jnp.float64)
                    ).astype(dtype) / scale
                )
            if isinstance(s_t, T.DecimalType):
                scale = 10.0 ** s_t.scale
                return wrap(lambda x: x.astype(dtype) / scale)
            return wrap(lambda x: x.astype(dtype))
        if isinstance(d_t, T.DecimalType):
            if (isinstance(s_t, T.DecimalType) and s_t.is_long) or (
                isinstance(s_t, T.DecimalType) and d_t.is_long
            ):
                return self._limb_rescale_cast(src, s_t, d_t)
            if d_t.is_long and s_t.is_integer:
                from trino_tpu.exec.aggregates import _limb_encode

                m = 10 ** d_t.scale
                return wrap(
                    lambda x: _limb_encode(x.astype(jnp.int64) * m)
                )
            if d_t.is_long and isinstance(s_t, (T.DoubleType, T.RealType)):
                # double -> decimal(>18): scale, round half away from
                # zero, split into limbs (float64 carries ~15-16
                # significant digits; beyond that the reference's
                # Int128 exactness is unattainable from a double too)
                m = 10.0 ** d_t.scale

                def ev_f2l(env, _m=m):
                    x, v = src.fn(env)
                    y = jnp.sign(x) * jnp.floor(jnp.abs(x) * _m + 0.5)
                    hi = jnp.floor(y / 4294967296.0)
                    lo = (y - hi * 4294967296.0).astype(jnp.int64)
                    return jnp.stack(
                        [hi.astype(jnp.int64), lo], axis=-1
                    ), v

                return CompiledExpr(ev_f2l, d_t, is_literal=src.is_literal)
            if d_t.is_long:
                raise NotImplementedError(f"cast {s_t} -> {d_t}")
            if isinstance(s_t, T.DecimalType):
                if d_t.scale >= s_t.scale:
                    m = 10 ** (d_t.scale - s_t.scale)
                    return wrap(lambda x: x * m)
                m = 10 ** (s_t.scale - d_t.scale)
                return wrap(lambda x: _div_round_half_up(x, m))
            if s_t.is_integer:
                m = 10 ** d_t.scale
                return wrap(lambda x: x.astype(jnp.int64) * m)
            if isinstance(s_t, (T.DoubleType, T.RealType)):
                # round half away from zero (reference: double->decimal
                # cast uses HALF_UP)
                m = 10.0 ** d_t.scale
                return wrap(
                    lambda x: (
                        jnp.sign(x) * jnp.floor(jnp.abs(x) * m + 0.5)
                    ).astype(jnp.int64)
                )
        if d_t.is_integer:
            dtype = d_t.np_dtype
            if isinstance(s_t, T.DecimalType):
                m = 10 ** s_t.scale
                return wrap(lambda x: _div_round_half_up(x, m).astype(dtype))
            if isinstance(s_t, (T.DoubleType, T.RealType)):
                # reference rounds (Math.round): floor(x + 0.5)
                return wrap(lambda x: jnp.floor(x + 0.5).astype(dtype))
            return wrap(lambda x: x.astype(dtype))
        if isinstance(d_t, T.DateType) and isinstance(s_t, T.VarcharType):
            # host-parse the dictionary once -> device gather by code;
            # unparseable values become NULL (reference: cast raises;
            # vectorized execution masks instead)
            if src.dictionary is None:
                raise NotImplementedError(
                    "cast varchar -> date requires a dictionary input"
                )
            vals, bad = [], []
            for v in src.dictionary.values:
                try:
                    vals.append(T.parse_date(str(v)))
                    bad.append(False)
                except (ValueError, TypeError):
                    vals.append(0)
                    bad.append(True)
            n = max(len(vals), 1)
            table = jnp.asarray(np.asarray(
                vals + [0] * (n - len(vals)), dtype=np.int32
            ))
            badt = jnp.asarray(np.asarray(
                bad + [True] * (n - len(bad)), dtype=np.bool_
            ))
            has_bad = any(bad)

            def ev_vc_date(env):
                data, valid = src.fn(env)
                code = jnp.clip(data, 0, n - 1)
                out = table[code]
                if has_bad:
                    okv = ~badt[code]
                    valid = okv if valid is None else (valid & okv)
                return out, valid

            return CompiledExpr(ev_vc_date, d_t, is_literal=src.is_literal)
        if isinstance(d_t, T.DateType) and isinstance(s_t, T.TimestampType):
            return wrap(
                lambda x: (x // T.MICROS_PER_DAY).astype(jnp.int32)
            )
        if isinstance(d_t, T.TimestampType) and isinstance(s_t, T.DateType):
            return wrap(
                lambda x: x.astype(jnp.int64) * T.MICROS_PER_DAY
            )
        if isinstance(d_t, T.VarcharType):
            raise NotImplementedError(f"cast {s_t} -> varchar not yet supported")
        raise NotImplementedError(f"cast {s_t} -> {d_t}")

    def _limb_rescale_cast(
        self, src: CompiledExpr, s_t: "T.DecimalType", d_t: "T.DecimalType"
    ) -> CompiledExpr:
        """Exact decimal rescale where either side is a two-limb
        decimal(>18): upscale multiplies limbs with carry
        normalization, downscale divides 96/64 rounding half away from
        zero (reference: SPI/type/Decimals.rescale over Int128)."""
        from trino_tpu.exec.aggregates import (
            _limb_div_round,
            _limb_encode,
            _limb_norm,
        )

        diff = d_t.scale - s_t.scale
        if 10 ** abs(diff) > 2**31:
            raise NotImplementedError(
                f"cast {s_t} -> {d_t}: rescale by >10^9"
            )
        s_long = s_t.is_long

        def ev(env):
            x, v = src.fn(env)
            if s_long:
                hi, lo = x[..., 0], x[..., 1]
            else:
                xi = x.astype(jnp.int64)
                hi, lo = xi >> jnp.int64(32), xi & jnp.int64(0xFFFFFFFF)
            if diff > 0:
                m = 10 ** diff
                hi, lo = _limb_norm(hi * m, lo * m)
            elif diff < 0:
                q = _limb_div_round(hi, lo, jnp.int64(10 ** (-diff)))
                if d_t.is_long:
                    return _limb_encode(q), v
                return q, v
            if d_t.is_long:
                return jnp.stack([hi, lo], axis=-1), v
            return hi * jnp.int64(4294967296) + lo, v

        return CompiledExpr(ev, d_t, is_literal=src.is_literal)

    # ---- calls -----------------------------------------------------------
    def _call(self, expr: Call) -> CompiledExpr:
        name = expr.name
        if name in ("and", "or"):
            return self._logic(expr)
        if name == "not":
            a = self.compile(expr.args[0])
            return CompiledExpr(
                lambda env: (lambda d, v: (~d, v))(*a.fn(env)), T.BOOLEAN
            )
        if name == "is_null":
            a = self.compile(expr.args[0])

            def ev_isnull(env):
                data, valid = a.fn(env)
                if valid is None:
                    return jnp.zeros(jnp.shape(data), dtype=jnp.bool_), None
                return ~valid, None

            return CompiledExpr(ev_isnull, T.BOOLEAN)
        if name == "if":
            return self._if(expr)
        if name == "coalesce":
            return self._coalesce(expr)
        if name == "in":
            return self._in(expr)
        if name in (
            "cardinality", "subscript", "contains",
            "map_keys", "map_values", "row_field",
        ):
            return self._array_fn(expr)
        if name in _STRING_PREDICATES:
            return self._string_predicate(expr)
        if name in _STRING_TRANSFORMS:
            return self._string_transform(expr)
        if name in _DICT_VALUE_FNS:
            return self._dict_value_fn(expr)
        if name == "nullif":
            a = self.compile(expr.args[0])
            cond = self.compile(expr.args[1])

            def ev_nullif(env, _a=a, _c=cond):
                d, v = _a.fn(env)
                cd, cv = _c.fn(env)
                # nullify only where the comparison is TRUE (an unknown
                # comparison keeps ``a`` — reference NullIf semantics)
                nullify = cd if cv is None else (cd & cv)
                nv = ~nullify if v is None else (v & ~nullify)
                return d, nv

            return CompiledExpr(ev_nullif, expr.type, a.dictionary)
        if name in ("eq", "ne", "lt", "le", "gt", "ge"):
            return self._comparison(expr)
        if name in ("add", "subtract", "multiply", "divide", "modulus"):
            return self._arith(expr)
        if name == "negate":
            a = self.compile(expr.args[0])
            return CompiledExpr(
                lambda env: (lambda d, v: (-d, v))(*a.fn(env)), expr.type
            )
        if name == "concat_cols":
            return self._concat_cols(expr)
        if name == "round":
            return self._round(expr)
        if name in _SIMPLE_FNS:
            return self._simple(expr)
        raise NotImplementedError(f"function {name} not implemented")

    def _concat_cols(self, expr: Call) -> CompiledExpr:
        """varchar || varchar between two dictionary-backed columns:
        the result dictionary is the (bounded) cross product of the
        operand dictionaries; the device op is one gather by the
        composite code a*|B| + b (the ConcatFunction analog under the
        dictionary-encode-early design)."""
        a = self.compile(expr.args[0])
        b = self.compile(expr.args[1])
        da, db = a.dictionary, b.dictionary
        if da is None or db is None:
            raise NotImplementedError(
                "|| requires dictionary-backed varchar operands"
            )
        na, nb = max(len(da), 1), max(len(db), 1)
        if na * nb > 4_000_000:
            raise NotImplementedError(
                f"|| dictionary product too large ({na}x{nb})"
            )
        pairs = np.asarray(
            [str(x) + str(y) for x in da.values for y in db.values]
            or [""],
            dtype=object,
        )
        new_dict, codes = StringDictionary.from_strings(pairs)
        remap = jnp.asarray(codes.astype(np.int32))

        def ev(env):
            ad, av = a.fn(env)
            bd, bv = b.fn(env)
            code = jnp.clip(
                ad.astype(jnp.int32) * nb + bd.astype(jnp.int32),
                0, na * nb - 1,
            )
            return jnp.take(remap, code, mode="clip"), _and_valid(av, bv)

        return CompiledExpr(ev, T.VARCHAR, new_dict)

    def _round(self, expr: Call) -> CompiledExpr:
        """round(x[, n]): half away from zero (reference
        MathFunctions.round — NOT banker's rounding). Decimal inputs
        round on the unscaled integer; the digit count must be a
        constant (it shapes the compiled program)."""
        a = self.compile(expr.args[0])
        ndig = 0
        if len(expr.args) > 1:
            d = expr.args[1]
            if not isinstance(d, Literal) or d.value is None:
                raise NotImplementedError(
                    "round() digit count must be a constant"
                )
            ndig = int(d.value)
        out_t = expr.type

        def ev(env):
            x, v = a.fn(env)
            if isinstance(a.type, T.DecimalType):
                s = a.type.scale
                if ndig >= s:
                    return x, v
                m = 10 ** (s - ndig)
                return _div_round_half_up(x, m) * m, v
            if a.type.is_integer:
                return x, v
            scale = jnp.asarray(10.0 ** ndig, dtype=x.dtype)
            y = x * scale
            return (
                jnp.sign(y) * jnp.floor(jnp.abs(y) + 0.5) / scale
            ).astype(out_t.np_dtype), v

        return CompiledExpr(ev, out_t)

    def _logic(self, expr: Call) -> CompiledExpr:
        parts = [self.compile(a) for a in expr.args]
        is_and = expr.name == "and"

        def ev(env):
            datas, valids = zip(*(p.fn(env) for p in parts))
            # Kleene: fill nulls with the identity, track "known" rows
            ident = True if is_and else False
            filled = [
                d if v is None else jnp.where(v, d, ident)
                for d, v in zip(datas, valids)
            ]
            out = filled[0]
            for f in filled[1:]:
                out = (out & f) if is_and else (out | f)
            if all(v is None for v in valids):
                return out, None
            # null unless every input known, or the result is decided
            known = None
            for v in valids:
                known = _and_valid(known, v)
            decided = out != ident  # AND: any false decides; OR: any true
            return out, known | decided if known is not None else None

        return CompiledExpr(ev, T.BOOLEAN)

    def _if(self, expr: Call) -> CompiledExpr:
        cond, then, els = (self.compile(a) for a in expr.args)
        out_dict = _merge_result_dicts(expr.type, [then, els])
        redict_then = _redict_fn(then, out_dict)
        redict_els = _redict_fn(els, out_dict)

        def ev(env):
            c_d, c_v = cond.fn(env)
            t_d, t_v = then.fn(env)
            e_d, e_v = els.fn(env)
            take_then = c_d if c_v is None else (c_d & c_v)
            data = jnp.where(take_then, redict_then(t_d), redict_els(e_d))
            if t_v is None and e_v is None:
                return data, None
            t_vv = t_v if t_v is not None else jnp.ones_like(take_then)
            e_vv = e_v if e_v is not None else jnp.ones_like(take_then)
            return data, jnp.where(take_then, t_vv, e_vv)

        return CompiledExpr(ev, expr.type, out_dict)

    def _coalesce(self, expr: Call) -> CompiledExpr:
        parts = [self.compile(a) for a in expr.args]
        out_dict = _merge_result_dicts(expr.type, parts)
        redicts = [_redict_fn(p, out_dict) for p in parts]

        def ev(env):
            data, valid = parts[0].fn(env)
            data = redicts[0](data)
            for p, rd in zip(parts[1:], redicts[1:]):
                if valid is None:
                    break
                d, v = p.fn(env)
                data = jnp.where(valid, data, rd(d))
                valid = valid | (v if v is not None else True)
            return data, valid

        return CompiledExpr(ev, expr.type, out_dict)

    def _in(self, expr: Call) -> CompiledExpr:
        value = expr.args[0]
        items = expr.args[1:]
        a = self.compile(value)
        if isinstance(value.type, T.VarcharType):
            # IN over literal strings -> dictionary LUT
            dict_ = a.dictionary
            if dict_ is None or not all(isinstance(i, Literal) for i in items):
                raise NotImplementedError("varchar IN requires literal list")
            wanted = {str(i.value) for i in items}
            lut = np.isin(dict_.values, list(wanted))
            lut_dev = jnp.asarray(lut) if len(lut) else jnp.zeros(1, dtype=jnp.bool_)

            def ev_str(env):
                data, valid = a.fn(env)
                return jnp.take(lut_dev, data, mode="clip"), valid

            return CompiledExpr(ev_str, T.BOOLEAN)
        compiled_items = [self.compile(i) for i in items]

        def ev(env):
            data, valid = a.fn(env)
            out = None
            any_null_item = None
            for ci in compiled_items:
                d, v = ci.fn(env)
                hit = data == d
                if v is not None:
                    hit = hit & v
                    item_null = ~v
                    any_null_item = (
                        item_null if any_null_item is None else any_null_item | item_null
                    )
                out = hit if out is None else out | hit
            if any_null_item is not None:
                # 3VL: no match + a NULL item -> NULL, not FALSE
                valid = _and_valid(valid, out | ~any_null_item)
            return out, valid

        return CompiledExpr(ev, T.BOOLEAN)

    def _comparison(self, expr: Call) -> CompiledExpr:
        lhs, rhs = expr.args
        a = self.compile(lhs)
        b = self.compile(rhs)
        if isinstance(lhs.type, T.VarcharType) or isinstance(rhs.type, T.VarcharType):
            return self._string_comparison(expr, a, b)
        a_long = isinstance(lhs.type, T.DecimalType) and lhs.type.is_long
        b_long = isinstance(rhs.type, T.DecimalType) and rhs.type.is_long
        if a_long or b_long:
            return self._limb_comparison(expr, a, b, a_long, b_long)
        if (
            isinstance(lhs.type, T.DecimalType)
            and isinstance(rhs.type, T.DecimalType)
            and lhs.type.scale != rhs.type.scale
        ):
            return self._mixed_scale_comparison(expr, a, b)
        op = _CMP_OPS[expr.name]

        def ev(env):
            a_d, a_v = a.fn(env)
            b_d, b_v = b.fn(env)
            return op(a_d, b_d), _and_valid(a_v, b_v)

        return CompiledExpr(ev, T.BOOLEAN)

    def _limb_comparison(
        self, expr: Call, a: CompiledExpr, b: CompiledExpr,
        a_long: bool, b_long: bool,
    ) -> CompiledExpr:
        """Exact comparison on two-limb decimals: numeric order equals
        lexicographic (hi, lo) order (lo canonical non-negative). Both
        sides must share the scale (analyzer coerces mixed-scale long
        comparisons through DOUBLE)."""
        if (a_long and b_long) and a.type.scale != b.type.scale:
            raise NotImplementedError(
                "mixed-scale long-decimal comparison"
            )
        if a_long != b_long:
            # widen the short side to limbs (same scale required)
            if a.type.scale != b.type.scale:
                raise NotImplementedError(
                    "mixed-scale long/short decimal comparison"
                )
        name = expr.name

        def limbs(c, is_long):
            def get(env):
                d, v = c.fn(env)
                if is_long:
                    return d[..., 0], d[..., 1], v
                return d >> jnp.int64(32), d & jnp.int64(0xFFFFFFFF), v

            return get

        ga = limbs(a, a_long)
        gb = limbs(b, b_long)

        def ev(env):
            ah, al, av = ga(env)
            bh, bl, bv = gb(env)
            if name == "eq":
                out = (ah == bh) & (al == bl)
            elif name == "ne":
                out = (ah != bh) | (al != bl)
            elif name == "lt":
                out = (ah < bh) | ((ah == bh) & (al < bl))
            elif name == "le":
                out = (ah < bh) | ((ah == bh) & (al <= bl))
            elif name == "gt":
                out = (ah > bh) | ((ah == bh) & (al > bl))
            else:  # ge
                out = (ah > bh) | ((ah == bh) & (al >= bl))
            return out, _and_valid(av, bv)

        return CompiledExpr(ev, T.BOOLEAN)

    def _mixed_scale_comparison(self, expr: Call, a: CompiledExpr, b: CompiledExpr) -> CompiledExpr:
        """Exact decimal comparison across scales without rescaling.

        Upscaling the coarse side by 10^(s_b - s_a) overflows int64 for
        large values (the reference sidesteps this with Int128 math,
        SPI/type/Decimals.java). Instead compare at the coarser scale:
        with m = 10^(s_b - s_a), q = floor(b / m), r = b - q*m (r >= 0):
        a*m <=> q*m + r reduces to comparing (a, 0) with (q, r)
        lexicographically.
        """
        name = expr.name
        if a.type.scale > b.type.scale:
            return self._mixed_scale_comparison(
                Call(
                    T.BOOLEAN,
                    _MIRRORED_CMP.get(name, name),
                    (expr.args[1], expr.args[0]),
                ),
                b, a,
            )
        m = 10 ** (b.type.scale - a.type.scale)

        def ev(env):
            a_d, a_v = a.fn(env)
            b_d, b_v = b.fn(env)
            q = b_d // m  # floor division: r in [0, m)
            r = b_d - q * m
            if name == "eq":
                out = (a_d == q) & (r == 0)
            elif name == "ne":
                out = (a_d != q) | (r != 0)
            elif name == "lt":
                out = (a_d < q) | ((a_d == q) & (r > 0))
            elif name == "le":
                out = a_d <= q
            elif name == "gt":
                out = a_d > q
            else:  # ge
                out = (a_d > q) | ((a_d == q) & (r == 0))
            return out, _and_valid(a_v, b_v)

        return CompiledExpr(ev, T.BOOLEAN)

    def _string_comparison(self, expr: Call, a: CompiledExpr, b: CompiledExpr) -> CompiledExpr:
        op = _CMP_OPS[expr.name]
        if a.is_literal and not b.is_literal:
            # normalize literal to the rhs with the mirrored operator
            name = _MIRRORED_CMP.get(expr.name, expr.name)
            return self._string_comparison(
                Call(T.BOOLEAN, name, (expr.args[1], expr.args[0])), b, a
            )
        # literal rhs: translate to a code comparison against the
        # column's dictionary (codes are in lexicographic order)
        if a.dictionary is not None and b.dictionary is not None:
            if b.is_literal:
                s = str(b.dictionary.values[0])
                code, exact = _code_bound(a.dictionary, s)

                # when the literal is absent, `code` is the insertion
                # point: x < s  <=>  x <= s  <=>  code(x) < code, and
                # x > s  <=>  x >= s  <=>  code(x) >= code
                name = expr.name
                if not exact:
                    name = {"le": "lt", "gt": "ge"}.get(name, name)

                def ev_lit(env):
                    a_d, a_v = a.fn(env)
                    if name == "eq":
                        r = (a_d == code) if exact else jnp.zeros_like(a_d, dtype=jnp.bool_)
                    elif name == "ne":
                        r = (a_d != code) if exact else jnp.ones_like(a_d, dtype=jnp.bool_)
                    else:
                        r = _CMP_OPS[name](a_d, jnp.asarray(code, dtype=a_d.dtype))
                    return r, a_v

                return CompiledExpr(ev_lit, T.BOOLEAN)
            if a.dictionary is b.dictionary:
                def ev_shared(env):
                    a_d, a_v = a.fn(env)
                    b_d, b_v = b.fn(env)
                    return op(a_d, b_d), _and_valid(a_v, b_v)

                return CompiledExpr(ev_shared, T.BOOLEAN)
            # distinct dictionaries: remap both onto their union at
            # compile time (codes stay order-preserving), compare codes
            merged, remap_a, remap_b = a.dictionary.union(b.dictionary)
            ra = _remap_gather(remap_a)
            rb = _remap_gather(remap_b)

            def ev_merged(env):
                a_d, a_v = a.fn(env)
                b_d, b_v = b.fn(env)
                return op(ra(a_d), rb(b_d)), _and_valid(a_v, b_v)

            return CompiledExpr(ev_merged, T.BOOLEAN)
        raise NotImplementedError(
            "varchar comparison requires a literal or a shared dictionary"
        )

    def _string_predicate(self, expr: Call) -> CompiledExpr:
        """LIKE & friends: host-eval over the dictionary -> device LUT."""
        a = self.compile(expr.args[0])
        if a.dictionary is None:
            raise NotImplementedError(f"{expr.name} requires a dictionary input")
        pattern = str(expr.args[1].value)  # type: ignore[attr-defined]
        if expr.name in ("like", "not_like"):
            rx = re.compile(_like_to_regex(pattern), re.DOTALL)
            matcher = rx.fullmatch
        elif expr.name == "regexp_like":
            # Trino regexp_like is a SEARCH (substring match), not a
            # full match (JoniRegexpFunctions.regexpLike)
            matcher = re.compile(pattern).search
        else:
            raise NotImplementedError(expr.name)
        lut = np.fromiter(
            (matcher(str(v)) is not None for v in a.dictionary.values),
            dtype=np.bool_,
            count=len(a.dictionary),
        )
        if expr.name == "not_like":
            lut = ~lut
        lut_dev = jnp.asarray(lut) if len(lut) else jnp.zeros(1, dtype=jnp.bool_)

        def ev(env):
            data, valid = a.fn(env)
            return jnp.take(lut_dev, data, mode="clip"), valid

        return CompiledExpr(ev, T.BOOLEAN)

    def _string_transform(self, expr: Call) -> CompiledExpr:
        """substr/lower/upper/...: transform dictionary values on host,
        re-sort, and compile to a device code-remap gather."""
        a = self.compile(expr.args[0])
        if a.dictionary is None:
            raise NotImplementedError(f"{expr.name} requires a dictionary input")
        f = _STRING_TRANSFORMS[expr.name]
        lits = [l.value for l in expr.args[1:]]  # type: ignore[attr-defined]
        try:
            raw = [f(str(v), *lits) for v in a.dictionary.values]
        except (re.error, IndexError) as e:
            raise ValueError(f"{expr.name}: {e}") from e
        # a transform may return None per value (regexp_extract with no
        # match is NULL, Trino semantics): carry a per-code null LUT
        null_lut = np.fromiter(
            (v is None for v in raw), dtype=np.bool_, count=len(raw)
        )
        transformed = np.asarray(
            ["" if v is None else v for v in raw], dtype=object
        )
        if len(transformed):
            new_dict, codes = StringDictionary.from_strings(transformed)
            remap = jnp.asarray(codes)
        else:
            new_dict, remap = StringDictionary(np.asarray([], dtype=object)), jnp.zeros(
                1, dtype=jnp.int32
            )
        has_nulls = bool(null_lut.any())
        null_dev = (
            jnp.asarray(null_lut) if has_nulls and len(null_lut)
            else None
        )

        def ev(env):
            data, valid = a.fn(env)
            out = jnp.take(remap, data, mode="clip")
            if null_dev is not None:
                notnull = ~jnp.take(null_dev, data, mode="clip")
                valid = notnull if valid is None else (valid & notnull)
            return out, valid

        return CompiledExpr(ev, expr.type, new_dict)

    def _dict_value_fn(self, expr: Call) -> CompiledExpr:
        """length/strpos/starts_with: evaluate per dictionary value on
        host, gather the result by code on device."""
        a = self.compile(expr.args[0])
        if a.dictionary is None:
            raise NotImplementedError(f"{expr.name} requires a dictionary input")
        f = _DICT_VALUE_FNS[expr.name]
        lits = [l.value for l in expr.args[1:]]  # type: ignore[attr-defined]
        out_dtype = expr.type.np_dtype
        table = np.asarray(
            [f(str(v), *lits) for v in a.dictionary.values],
            dtype=out_dtype,
        )
        if not len(table):
            table = np.zeros(1, dtype=out_dtype)
        dev_table = jnp.asarray(table)

        def ev(env):
            data, valid = a.fn(env)
            return jnp.take(dev_table, data, mode="clip"), valid

        return CompiledExpr(ev, expr.type)

    def _arith(self, expr: Call) -> CompiledExpr:
        lhs, rhs = expr.args
        a = self.compile(lhs)
        b = self.compile(rhs)
        name = expr.name
        out_t = expr.type

        if isinstance(out_t, T.DecimalType):
            return self._decimal_arith(expr, a, b)

        ops = {
            "add": jnp.add,
            "subtract": jnp.subtract,
            "multiply": jnp.multiply,
        }
        if name in ops:
            op = ops[name]

            def ev(env):
                a_d, a_v = a.fn(env)
                b_d, b_v = b.fn(env)
                return op(a_d, b_d).astype(out_t.np_dtype), _and_valid(a_v, b_v)

            return CompiledExpr(ev, out_t)
        if name == "divide":
            if out_t.is_integer:
                def ev_idiv(env):
                    a_d, a_v = a.fn(env)
                    b_d, b_v = b.fn(env)
                    safe = jnp.where(b_d == 0, 1, b_d)
                    q = _int_div_trunc(a_d, safe)
                    # division by zero nulls the row (the reference
                    # raises DIVISION_BY_ZERO; vectorized execution
                    # cannot raise per-row — masked at output instead)
                    return q.astype(out_t.np_dtype), _and_valid(
                        _and_valid(a_v, b_v), b_d != 0
                    )

                return CompiledExpr(ev_idiv, out_t)

            def ev_fdiv(env):
                a_d, a_v = a.fn(env)
                b_d, b_v = b.fn(env)
                return (a_d / b_d).astype(out_t.np_dtype), _and_valid(a_v, b_v)

            return CompiledExpr(ev_fdiv, out_t)
        if name == "modulus":
            def ev_mod(env):
                a_d, a_v = a.fn(env)
                b_d, b_v = b.fn(env)
                safe = jnp.where(b_d == 0, 1, b_d)
                r = a_d - _int_div_trunc(a_d, safe) * safe
                return r.astype(out_t.np_dtype), _and_valid(
                    _and_valid(a_v, b_v), b_d != 0
                )

            return CompiledExpr(ev_mod, out_t)
        raise NotImplementedError(name)

    def _decimal_arith(self, expr: Call, a: CompiledExpr, b: CompiledExpr) -> CompiledExpr:
        """Decimal arithmetic on unscaled int64 (reference semantics:
        MAIN/type/DecimalOperators.java — round half-up on divide)."""
        out_t: T.DecimalType = expr.type  # type: ignore[assignment]
        name = expr.name
        s_a = a.type.scale if isinstance(a.type, T.DecimalType) else 0
        s_b = b.type.scale if isinstance(b.type, T.DecimalType) else 0

        def ev(env):
            a_d, a_v = a.fn(env)
            b_d, b_v = b.fn(env)
            valid = _and_valid(a_v, b_v)
            a_i = a_d.astype(jnp.int64)
            b_i = b_d.astype(jnp.int64)
            if name in ("add", "subtract"):
                a_i = a_i * 10 ** (out_t.scale - s_a)
                b_i = b_i * 10 ** (out_t.scale - s_b)
                out = a_i + b_i if name == "add" else a_i - b_i
            elif name == "multiply":
                out = a_i * b_i  # scale s_a + s_b == out_t.scale
            elif name == "divide":
                # rescale so that quotient has out_t.scale
                shift = out_t.scale - s_a + s_b
                num = a_i * 10**shift
                safe = jnp.where(b_i == 0, 1, b_i)
                out = _div_round_half_up(num, safe)
                valid = _and_valid(valid, b_i != 0)  # null the /0 rows
            elif name == "modulus":
                safe = jnp.where(b_i == 0, 1, b_i)
                out = a_i - _int_div_trunc(a_i, safe) * safe
                valid = _and_valid(valid, b_i != 0)
            else:
                raise NotImplementedError(name)
            return out, valid

        return CompiledExpr(ev, out_t)

    def _simple(self, expr: Call) -> CompiledExpr:
        parts = [self.compile(a) for a in expr.args]
        f = _SIMPLE_FNS[expr.name]
        out_t = expr.type

        def ev(env):
            vals = [p.fn(env) for p in parts]
            datas = [d for d, _ in vals]
            valid = None
            for _, v in vals:
                valid = _and_valid(valid, v)
            return f(*datas).astype(out_t.np_dtype), valid

        return CompiledExpr(ev, out_t)


# ---- helpers -------------------------------------------------------------

def _literal_device_value(expr: Literal):
    v = expr.value
    if isinstance(expr.type, T.DateType) and isinstance(v, str):
        return T.parse_date(v)
    if isinstance(expr.type, T.TimestampType) and isinstance(v, str):
        return T.parse_timestamp(v)
    if isinstance(expr.type, T.DecimalType):
        from decimal import Decimal

        return int(
            (Decimal(str(v)) * (10 ** expr.type.scale)).to_integral_value()
        )
    return v


def _int_div_trunc(a, b):
    """C-style truncating integer division (SQL semantics), vs
    python/jnp floor division."""
    q = a // b
    r = a - q * b
    fix = (r != 0) & ((a < 0) != (b < 0))
    return q + jnp.where(fix, 1, 0)


def _div_round_half_up(a, b):
    """Integer divide rounding half away from zero (Trino decimal rule,
    MAIN reference io.trino.spi.type.Decimals.rescale)."""
    sign = jnp.where((a < 0) != (b < 0), -1, 1)
    aa = jnp.abs(a)
    ab = jnp.abs(b)
    return sign * ((aa + ab // 2) // ab)


def _like_to_regex(pattern: str, escape: str | None = None) -> str:
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if escape and ch == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return "".join(out)


def _code_bound(d: StringDictionary, s: str) -> tuple[int, bool]:
    """(code position of s in dictionary, whether s is present).

    For non-equality comparisons the insertion point works as the
    bound: x < s  <=>  code(x) < insertion_point when s absent.
    """
    i = int(np.searchsorted(d.values, s))
    exact = i < len(d.values) and d.values[i] == s
    if not exact and i == len(d.values):
        # all values < s: use a code past the end
        return len(d.values), False
    return i, exact


def _merge_result_dicts(out_type, parts):
    if not isinstance(out_type, T.VarcharType):
        return None
    # a dictionary-less varchar branch is acceptable only as a typed
    # NULL literal (validity always False — e.g. CASE WHEN ... THEN col
    # END with an implicit NULL else): it contributes an empty
    # dictionary. Hash-pool-coded columns also carry no dictionary but
    # are [n,2] code lanes — merging them silently would corrupt, so
    # they keep the loud error.
    if any(p.dictionary is None and not p.is_literal for p in parts):
        raise NotImplementedError(
            "varchar branches must be dictionary-backed"
        )
    empty = StringDictionary(np.asarray([], dtype=object))
    dicts = [p.dictionary if p.dictionary is not None else empty for p in parts]
    merged = dicts[0]
    for d in dicts[1:]:
        if d is not merged:
            merged, _, _ = merged.union(d)
    return merged


def _redict_fn(part: CompiledExpr, merged: StringDictionary | None):
    """Compile-time code remap onto a merged dictionary (device gather)."""
    if merged is None or part.dictionary is merged or part.dictionary is None:
        # dictionary-less parts are typed NULL literals: their codes
        # are never valid, no remap needed
        return lambda data: data
    remap = np.searchsorted(merged.values, part.dictionary.values).astype(np.int32)
    return _remap_gather(remap)


def _remap_gather(remap: np.ndarray):
    if len(remap) == 0:
        return lambda data: data
    remap_dev = jnp.asarray(remap)
    return lambda data: jnp.take(remap_dev, data, mode="clip")


_CMP_OPS = {
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
}

#: operator under argument swap: a OP b == b MIRROR(OP) a
_MIRRORED_CMP = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}

_STRING_PREDICATES = {"like", "not_like", "regexp_like"}

_STRING_TRANSFORMS: dict[str, Callable] = {
    "substr": lambda s, start, length=None: (
        s[int(start) - 1 : int(start) - 1 + int(length)]
        if length is not None
        else s[int(start) - 1 :]
    ),
    "lower": lambda s: s.lower(),
    "upper": lambda s: s.upper(),
    "trim": lambda s: s.strip(),
    "ltrim": lambda s: s.lstrip(),
    "rtrim": lambda s: s.rstrip(),
    "reverse": lambda s: s[::-1],
    "replace": lambda s, find, repl="": s.replace(find, repl),
    # || with a literal operand (ConcatFunction over dictionary values)
    "concat_suffix": lambda s, suffix: s + str(suffix),
    "concat_prefix": lambda s, prefix: str(prefix) + s,
    # Trino regex semantics (JoniRegexpFunctions): extract returns the
    # group (NULL-as-empty here: dictionary transforms cannot produce
    # NULL) or '' when unmatched; replace substitutes every match
    "regexp_extract": lambda s, pattern, group=0: (
        (lambda m: (m.group(int(group)) or "") if m else None)(
            re.search(str(pattern), s)
        )
    ),
    "regexp_replace": lambda s, pattern, repl="": re.sub(
        str(pattern),
        _java_replacement(
            str(repl), re.compile(str(pattern)).groups
        ),
        s,
    ),
}


def _java_replacement(repl: str, n_groups: int) -> str:
    r"""Java appendReplacement semantics (what Trino's regexp_replace
    uses) -> python re.sub replacement: $N group references backtrack
    to the largest VALID group number ($10 with one group = group 1 +
    literal '0'); backslash escapes the next character literally; the
    output escapes python's own backslash handling."""
    def lit(c: str) -> str:
        return "\\\\" if c == "\\" else c

    out = []
    i = 0
    while i < len(repl):
        c = repl[i]
        if c == "\\" and i + 1 < len(repl):
            out.append(lit(repl[i + 1]))
            i += 2
            continue
        if c == "$":
            j = i + 1
            while j < len(repl) and repl[j].isdigit():
                j += 1
            # backtrack to the largest group number the pattern has
            while j > i + 1 and int(repl[i + 1:j]) > max(n_groups, 0) \
                    and j - (i + 1) > 1:
                j -= 1
            if j > i + 1:
                out.append(f"\\g<{repl[i + 1:j]}>")
                i = j
                continue
        out.append(lit(c))
        i += 1
    return "".join(out)

#: varchar -> numeric/boolean per-dictionary-value functions: evaluate
#: on the (small) dictionary host-side, gather by code on device
_DICT_VALUE_FNS: dict[str, Callable] = {
    "length": lambda s: len(s),
    "strpos": lambda s, sub: s.find(sub) + 1,
    "starts_with": lambda s, p: s.startswith(p),
}


def _extract_civil(days):
    """Vectorized Gregorian calendar decomposition of epoch days
    (days-from-civil inverse, Howard Hinnant's algorithm)."""
    z = days.astype(jnp.int64) + 719_468
    era = z // 146_097  # jnp // is floor division — no truncation offset
    doe = z - era * 146_097
    yoe = (doe - doe // 1460 + doe // 36_524 - doe // 146_096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def _days_from_civil(y, m, d):
    """Vectorized inverse of _extract_civil: (y, m, d) -> epoch days
    (Howard Hinnant's days_from_civil)."""
    y = y - (m <= 2)
    era = y // 400
    yoe = y - era * 400
    mp = m + jnp.where(m > 2, -3, 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146_097 + doe - 719_468


def _days_in_month(y, m):
    ny = y + (m == 12)
    nm = jnp.where(m == 12, 1, m + 1)
    return _days_from_civil(ny, nm, 1) - _days_from_civil(y, m, 1)


def _iso_dow(days):
    """ISO day-of-week of epoch days: Monday=1..Sunday=7 (epoch day 0,
    1970-01-01, is a Thursday -> 4). Reference: DateTimeFunctions
    dayOfWeekFromDate."""
    return (days.astype(jnp.int64) + 3) % 7 + 1


def _doy(days):
    y, _, _ = _extract_civil(days)
    return days.astype(jnp.int64) - _days_from_civil(y, jnp.int64(1), jnp.int64(1)) + 1


def _iso_week(days):
    """ISO-8601 week of year: the week containing this day's Thursday
    determines the year; weeks start Monday (reference:
    DateTimeFunctions.weekFromDate via ISOChronology weekOfWeekyear)."""
    days = days.astype(jnp.int64)
    thursday = days - (_iso_dow(days) - 4)
    ty, _, _ = _extract_civil(thursday)
    jan1 = _days_from_civil(ty, jnp.int64(1), jnp.int64(1))
    return (thursday - jan1) // 7 + 1


def _add_months_days(days, months):
    """date + n months with end-of-month day clamping (reference:
    DateTimeFunctions.addFieldValueDate -> Joda addMonths semantics)."""
    y, m, d = _extract_civil(days)
    m0 = y * 12 + (m - 1) + months.astype(jnp.int64)
    y2 = m0 // 12
    m2 = m0 - y2 * 12 + 1
    d2 = jnp.minimum(d, _days_in_month(y2, m2))
    return _days_from_civil(y2, m2, d2)


def _months_between(a, b):
    """Full months from date a to date b: the largest n with
    a + n months <= b (sign-symmetric; reference:
    DateTimeFunctions.diffDate('month') -> Joda monthsBetween)."""
    a = a.astype(jnp.int64)
    b = b.astype(jnp.int64)
    ya, ma, _ = _extract_civil(a)
    yb, mb, _ = _extract_civil(b)
    m = (yb * 12 + mb) - (ya * 12 + ma)
    cand = _add_months_days(a, m)
    m = m - jnp.where((m > 0) & (cand > b), 1, 0)
    return m + jnp.where((m < 0) & (cand < b), 1, 0)


def _ts_add_months(x, m):
    x = x.astype(jnp.int64)
    days = x // 86_400_000_000
    tod = x % 86_400_000_000
    return _add_months_days(days, m) * 86_400_000_000 + tod


def _ts_months_between(a, b):
    """Full months between instants: time-of-day participates (Joda
    monthsBetween over instants — a month has not elapsed until the
    end instant reaches start + n months to the microsecond)."""
    a = a.astype(jnp.int64)
    b = b.astype(jnp.int64)
    m = _months_between(a // 86_400_000_000, b // 86_400_000_000)
    cand = _ts_add_months(a, m)
    m = m - jnp.where((m > 0) & (cand > b), 1, 0)
    return m + jnp.where((m < 0) & (cand < b), 1, 0)


def _ts_trunc(unit_micros):
    def f(x):
        x = x.astype(jnp.int64)
        return x - x % unit_micros  # jnp % floors: correct pre-epoch

    return f


def _ts_trunc_civil(date_trunc_fn):
    """Truncate a timestamp through its civil date component."""

    def f(x):
        days = x.astype(jnp.int64) // 86_400_000_000
        return date_trunc_fn(days) * 86_400_000_000

    return f


def _date_trunc_year(d):
    y, _, _ = _extract_civil(d)
    return _days_from_civil(y, jnp.int64(1), jnp.int64(1))


def _date_trunc_quarter(d):
    y, m, _ = _extract_civil(d)
    return _days_from_civil(y, ((m - 1) // 3) * 3 + 1, jnp.int64(1))


def _date_trunc_month(d):
    y, m, _ = _extract_civil(d)
    return _days_from_civil(y, m, jnp.int64(1))


def _date_trunc_week(d):
    return d.astype(jnp.int64) - (_iso_dow(d) - 1)


_SIMPLE_FNS: dict[str, Callable] = {
    "extract_year": lambda d: _extract_civil(d)[0],
    "extract_month": lambda d: _extract_civil(d)[1],
    "extract_day": lambda d: _extract_civil(d)[2],
    "abs": jnp.abs,
    "sqrt": jnp.sqrt,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "exp": jnp.exp,
    "ln": jnp.log,
    "log2": jnp.log2,
    "log10": jnp.log10,
    "power": lambda a, b: jnp.power(
        a.astype(jnp.float64), b.astype(jnp.float64)
    ),
    "cbrt": jnp.cbrt,
    "sign": jnp.sign,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "asin": jnp.arcsin,
    "acos": jnp.arccos,
    "atan": jnp.arctan,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    # timestamp fields (micros since epoch)
    "extract_hour": lambda x: (x // 3_600_000_000) % 24,
    "extract_minute": lambda x: (x // 60_000_000) % 60,
    "extract_second": lambda x: (x // 1_000_000) % 60,
    # date/time family (reference: MAIN/operator/scalar/
    # DateTimeFunctions.java:73 — civil-calendar decomposition runs
    # vectorized on device, no per-row host work)
    "extract_quarter": lambda d: (_extract_civil(d)[1] - 1) // 3 + 1,
    "extract_day_of_week": _iso_dow,
    "extract_day_of_year": _doy,
    "extract_week": _iso_week,
    "extract_year_of_week": lambda d: _extract_civil(
        d.astype(jnp.int64) - (_iso_dow(d) - 4)
    )[0],
    "last_day_of_month": lambda d: (
        lambda y, m, _d: _days_from_civil(y, m, _days_in_month(y, m))
    )(*_extract_civil(d)),
    "date_trunc_year": _date_trunc_year,
    "date_trunc_quarter": _date_trunc_quarter,
    "date_trunc_month": _date_trunc_month,
    "date_trunc_week": _date_trunc_week,
    "date_trunc_day": lambda d: d,
    "ts_trunc_year": _ts_trunc_civil(_date_trunc_year),
    "ts_trunc_quarter": _ts_trunc_civil(_date_trunc_quarter),
    "ts_trunc_month": _ts_trunc_civil(_date_trunc_month),
    "ts_trunc_week": _ts_trunc_civil(_date_trunc_week),
    "ts_trunc_day": _ts_trunc(86_400_000_000),
    "ts_trunc_hour": _ts_trunc(3_600_000_000),
    "ts_trunc_minute": _ts_trunc(60_000_000),
    "ts_trunc_second": _ts_trunc(1_000_000),
    "add_months": _add_months_days,
    "ts_add_months": _ts_add_months,
    "months_between": _months_between,
    "ts_months_between": _ts_months_between,
}
