"""Unified telemetry: trace spans, a process-wide metrics registry, and
Prometheus text rendering.

Three cooperating pieces (mirroring the reference engine's airlift stats +
OpenTelemetry tracing split):

* **Spans** — hierarchical wall-clock spans (query → planning → stage →
  task → operator), serialisable so worker-side subtrees can ride back on
  task-status responses and stitch into the coordinator's query trace.
  Exportable as Chrome trace-event JSON (chrome://tracing / Perfetto).
* **MetricsRegistry** — labelled counters / gauges / histograms rendered
  in Prometheus text exposition format; a process-global ``REGISTRY`` is
  served at ``GET /v1/metrics`` by both coordinator and worker.
* **XLA compile hooks** — a ``jax.monitoring`` duration listener feeding
  compile count/seconds counters, plus ``CountingCache`` wrapping the
  executors' jit caches for hit/miss rates.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

__all__ = [
    "Span",
    "Trace",
    "Tracer",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "CountingCache",
    "REGISTRY",
    "install_jax_compile_hook",
    "render_prometheus",
]


def _now_ms() -> float:
    """Epoch milliseconds — spans from different processes share this clock."""
    return time.time() * 1000.0


def _new_id() -> str:
    return uuid.uuid4().hex[:16]


# ---------------------------------------------------------------------------
# Trace spans
# ---------------------------------------------------------------------------


@dataclass
class Span:
    name: str
    kind: str = "internal"  # query|planning|stage|task|operator|spool|rpc|...
    span_id: str = field(default_factory=_new_id)
    parent_id: Optional[str] = None
    trace_id: str = ""
    start_ms: float = field(default_factory=_now_ms)
    duration_ms: float = 0.0
    node: str = ""  # which process produced this span ("" = coordinator)
    attrs: Dict[str, Any] = field(default_factory=dict)
    children: List["Span"] = field(default_factory=list)
    _t0: float = field(default_factory=time.perf_counter, repr=False)
    _open: bool = field(default=True, repr=False)

    def finish(self) -> "Span":
        if self._open:
            self.duration_ms = (time.perf_counter() - self._t0) * 1000.0
            self._open = False
        return self

    def child(self, name: str, kind: str = "internal", **attrs: Any) -> "Span":
        sp = Span(name=name, kind=kind, parent_id=self.span_id,
                  trace_id=self.trace_id, node=self.node, attrs=dict(attrs))
        self.children.append(sp)
        return sp

    def walk(self) -> Iterator["Span"]:
        yield self
        for c in self.children:
            yield from c.walk()

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "kind": self.kind,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "trace_id": self.trace_id,
            "start_ms": self.start_ms,
            "duration_ms": self.duration_ms,
            "node": self.node,
            "attrs": dict(self.attrs),
            "children": [c.to_dict() for c in self.children],
        }

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Span":
        sp = Span(
            name=d.get("name", "?"),
            kind=d.get("kind", "internal"),
            span_id=d.get("span_id") or _new_id(),
            parent_id=d.get("parent_id"),
            trace_id=d.get("trace_id", ""),
            start_ms=float(d.get("start_ms", 0.0)),
            duration_ms=float(d.get("duration_ms", 0.0)),
            node=d.get("node", ""),
            attrs=dict(d.get("attrs") or {}),
        )
        sp._open = False
        sp.children = [Span.from_dict(c) for c in d.get("children") or []]
        return sp


class Trace:
    """A completed span tree for one query, rooted at the query span."""

    def __init__(self, root: Span) -> None:
        self.root = root
        self.trace_id = root.trace_id

    def spans(self) -> List[Span]:
        return list(self.root.walk())

    def find(self, name: Optional[str] = None, kind: Optional[str] = None) -> List[Span]:
        out = []
        for sp in self.root.walk():
            if name is not None and sp.name != name:
                continue
            if kind is not None and sp.kind != kind:
                continue
            out.append(sp)
        return out

    def to_dict(self) -> Dict[str, Any]:
        return self.root.to_dict()

    def to_chrome_json(self) -> str:
        """Render as Chrome trace-event JSON (``ph:"X"`` complete events).

        Loadable in chrome://tracing or https://ui.perfetto.dev. ``pid``
        groups spans by producing node; ``ts``/``dur`` are microseconds.
        """
        events: List[Dict[str, Any]] = []
        pids: Dict[str, int] = {}
        for sp in self.root.walk():
            pid = pids.setdefault(sp.node or "coordinator", len(pids) + 1)
            events.append({
                "name": sp.name,
                "cat": sp.kind,
                "ph": "X",
                "ts": sp.start_ms * 1000.0,
                "dur": max(sp.duration_ms, 0.0) * 1000.0,
                "pid": pid,
                "tid": 1,
                "args": dict(sp.attrs, span_id=sp.span_id,
                             parent_id=sp.parent_id or ""),
            })
        meta = [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 1,
             "args": {"name": node}}
            for node, pid in pids.items()
        ]
        return json.dumps({"traceEvents": meta + events,
                           "displayTimeUnit": "ms"}, indent=None)


class Tracer:
    """Builds one query's span tree; cheap enough to always be on.

    The coordinator (or local engine) owns a Tracer per query. Workers
    build detached task subtrees with ``parent_id`` taken from the trace
    context shipped on ``/v1/stagetask`` and return them serialised on the
    task-status response; the coordinator stitches them in with
    :meth:`attach`.
    """

    def __init__(self, query_id: str = "", trace_id: Optional[str] = None,
                 node: str = "") -> None:
        self.trace_id = trace_id or _new_id()
        self.node = node
        self.root: Optional[Span] = None
        self._stack: List[Span] = []
        if query_id:
            self.root = Span(name=f"query {query_id}", kind="query",
                             trace_id=self.trace_id, node=node,
                             attrs={"query_id": query_id})
            self._stack = [self.root]

    # -- span lifecycle ----------------------------------------------------
    def start(self, name: str, kind: str = "internal", parent: Optional[Span] = None,
              **attrs: Any) -> Span:
        """Open a span under ``parent`` (default: top of stack / detached root)."""
        if parent is None and self._stack:
            parent = self._stack[-1]
        if parent is not None:
            sp = parent.child(name, kind, **attrs)
        else:
            sp = Span(name=name, kind=kind, trace_id=self.trace_id,
                      node=self.node, attrs=dict(attrs))
            if self.root is None:
                self.root = sp
        return sp

    def span(self, name: str, kind: str = "internal", **attrs: Any) -> "_SpanCtx":
        return _SpanCtx(self, name, kind, attrs)

    def attach(self, span_dict: Dict[str, Any]) -> Optional[Span]:
        """Stitch a serialised (worker-side) subtree under its parent span."""
        try:
            sub = Span.from_dict(span_dict)
        except Exception:
            return None
        if self.root is None:
            return None
        parent = None
        if sub.parent_id:
            for sp in self.root.walk():
                if sp.span_id == sub.parent_id:
                    parent = sp
                    break
        (parent or self.root).children.append(sub)
        return sub

    def context(self, parent: Optional[Span] = None) -> Dict[str, str]:
        """Trace-context dict to ship across RPC boundaries."""
        sp = parent or (self._stack[-1] if self._stack else self.root)
        return {"trace_id": self.trace_id,
                "parent_span_id": sp.span_id if sp is not None else ""}

    def finish(self) -> Trace:
        for sp in reversed(self._stack):
            sp.finish()
        if self.root is None:
            self.root = Span(name="query", kind="query", trace_id=self.trace_id,
                             node=self.node)
        self.root.finish()
        return Trace(self.root)


class _SpanCtx:
    def __init__(self, tracer: Tracer, name: str, kind: str, attrs: Dict[str, Any]):
        self._tracer = tracer
        self._name, self._kind, self._attrs = name, kind, attrs
        self.span: Optional[Span] = None

    def __enter__(self) -> Span:
        self.span = self._tracer.start(self._name, self._kind, **self._attrs)
        self._tracer._stack.append(self.span)
        return self.span

    def __exit__(self, *exc: Any) -> None:
        if self.span is not None:
            self.span.finish()
            stack = self._tracer._stack
            if stack and stack[-1] is self.span:
                stack.pop()


# ---------------------------------------------------------------------------
# Metrics registry (Prometheus text exposition)
# ---------------------------------------------------------------------------


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_labels(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    esc = lambda v: v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    return "{" + ",".join(f'{k}="{esc(v)}"' for k, v in key) + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str) -> None:
        self.name = name
        self.help = help
        self._lock = threading.Lock()

    def render(self) -> List[str]:  # pragma: no cover - overridden
        return []

    def header(self) -> List[str]:
        # exposition format 0.0.4: HELP text escapes backslash+newline
        help_esc = self.help.replace("\\", "\\\\").replace("\n", "\\n")
        return [f"# HELP {self.name} {help_esc}",
                f"# TYPE {self.name} {self.kind}"]


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help: str) -> None:
        super().__init__(name, help)
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._values.values())

    def render(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        if not items:
            items = [((), 0.0)]
        return [f"{self.name}{_render_labels(k)} {_fmt_val(v)}" for k, v in items]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help: str) -> None:
        super().__init__(name, help)
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self._callbacks: List[Callable[[], Dict[Tuple[Tuple[str, str], ...], float]]] = []

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def add(self, amount: float, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._values.values())

    def render(self) -> List[str]:
        with self._lock:
            merged = dict(self._values)
        if not merged:
            merged = {(): 0.0}
        return [f"{self.name}{_render_labels(k)} {_fmt_val(v)}"
                for k, v in sorted(merged.items())]


_DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                    1.0, 2.5, 5.0, 10.0)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str,
                 buckets: Sequence[float] = _DEFAULT_BUCKETS) -> None:
        super().__init__(name, help)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple[Tuple[str, str], ...], List[int]] = {}
        self._sums: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self._totals: Dict[Tuple[Tuple[str, str], ...], int] = {}

    def observe(self, value: float, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def count(self, **labels: str) -> int:
        with self._lock:
            return self._totals.get(_label_key(labels), 0)

    def total_count(self) -> int:
        with self._lock:
            return sum(self._totals.values())

    def total_sum(self) -> float:
        with self._lock:
            return sum(self._sums.values())

    def render(self) -> List[str]:
        out: List[str] = []
        with self._lock:
            keys = sorted(self._counts)
            for key in keys:
                counts = self._counts[key]
                for i, b in enumerate(self.buckets):
                    lk = key + (("le", _fmt_val(b)),)
                    out.append(f"{self.name}_bucket{_render_labels(tuple(sorted(lk)))} {counts[i]}")
                lk = key + (("le", "+Inf"),)
                out.append(f"{self.name}_bucket{_render_labels(tuple(sorted(lk)))} {self._totals[key]}")
                out.append(f"{self.name}_sum{_render_labels(key)} {_fmt_val(self._sums[key])}")
                out.append(f"{self.name}_count{_render_labels(key)} {self._totals[key]}")
        return out


def _fmt_val(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class MetricsRegistry:
    """Registry of named metric families; renders Prometheus text format."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get(self, cls: type, name: str, help: str, **kw: Any) -> Any:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, **kw)
                self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = _DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def render(self) -> str:
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.header())
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, float]:
        """Flat scalar snapshot of every family — counters and gauges
        collapse across label sets; histograms report ``_count`` and
        ``_sum``. The before/after substrate of diagnostic-bundle metric
        deltas and the cluster time-series recorder's self-scrape."""
        with self._lock:
            metrics = [self._metrics[n] for n in sorted(self._metrics)]
        out: Dict[str, float] = {}
        for m in metrics:
            if isinstance(m, Histogram):
                out[f"{m.name}_count"] = float(m.total_count())
                out[f"{m.name}_sum"] = float(m.total_sum())
            elif isinstance(m, (Counter, Gauge)):
                out[m.name] = float(m.total())
        return out


#: Process-global registry served at GET /v1/metrics.
REGISTRY = MetricsRegistry()


def render_prometheus() -> str:
    return REGISTRY.render()


# -- well-known families, created eagerly so /v1/metrics always lists them --

QUERIES_TOTAL = REGISTRY.counter(
    "trino_queries_total", "Completed queries by terminal state")
QUERY_RETRIES = REGISTRY.counter(
    "trino_query_retries_total", "Whole-query re-executions (retry_policy=QUERY)")
TASKS_RETRIED = REGISTRY.counter(
    "trino_tasks_retried_total", "Task attempts re-run after failure")
TASKS_SPECULATED = REGISTRY.counter(
    "trino_tasks_speculated_total", "Speculative duplicate task attempts launched")
SPECULATION_WINS = REGISTRY.counter(
    "trino_speculation_wins_total", "Speculative attempts that finished first")
WORKERS_READMITTED = REGISTRY.counter(
    "trino_workers_readmitted_total", "Workers re-admitted after exclusion")
CHAOS_INJECTIONS = REGISTRY.counter(
    "trino_chaos_injections_total", "Faults fired by the chaos injector, by site")
SPOOL_BYTES_WRITTEN = REGISTRY.counter(
    "trino_spool_bytes_written_total", "Bytes written to exchange spool files")
SPOOL_BYTES_READ = REGISTRY.counter(
    "trino_spool_bytes_read_total", "Bytes read back from exchange spool files")
SPOOL_CRC_FAILURES = REGISTRY.counter(
    "trino_spool_crc_failures_total", "Spool partition reads failing CRC/manifest checks")
EXCHANGE_ROWS = REGISTRY.counter(
    "trino_exchange_rows_total", "Rows moved through mesh exchanges")
EXCHANGE_BYTES = REGISTRY.counter(
    "trino_exchange_bytes_total", "Bytes moved through mesh exchanges")
EXCHANGE_DIRECT_BYTES = REGISTRY.counter(
    "trino_exchange_direct_bytes_total",
    "Exchange bytes served straight from producer memory buffers")
EXCHANGE_SPOOLED_BYTES = REGISTRY.counter(
    "trino_exchange_spooled_bytes_total",
    "Exchange bytes read back from the on-disk spool")
EXCHANGE_BUFFER_RESERVED = REGISTRY.gauge(
    "trino_exchange_buffer_reserved_bytes",
    "Bytes currently held in the worker's direct-exchange buffer pool")
EXCHANGE_BUFFER_EVICTIONS = REGISTRY.counter(
    "trino_exchange_buffer_evictions_total",
    "Direct-exchange buffer entries evicted before every consumer fetched")
MEMORY_RESERVED = REGISTRY.gauge(
    "trino_memory_pool_reserved_bytes", "Currently reserved bytes per memory pool")
MEMORY_PEAK = REGISTRY.gauge(
    "trino_memory_pool_peak_bytes", "High-water reserved bytes per memory pool")
MEMORY_KILLS = REGISTRY.counter(
    "trino_memory_kills_total", "Queries killed by the cluster memory manager")
RPC_LATENCY = REGISTRY.histogram(
    "trino_rpc_latency_seconds", "Coordinator-side fleet RPC latency by op",
    # the poll path lives under 10ms on a local fleet — the default
    # buckets put every sample in the first two and hide the tail
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
             0.25, 0.5, 1.0, 2.5, 10.0))
OPERATOR_SELF_TIME = REGISTRY.histogram(
    "trino_operator_self_time_seconds",
    "Per-operator self time on workers, by operator node type",
    # operators span sub-ms (cached dispatch) to whole-query seconds
    buckets=(0.0005, 0.002, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
             5.0, 15.0, 60.0))
XLA_COMPILES = REGISTRY.counter(
    "trino_xla_compile_total", "XLA backend compilations observed via jax.monitoring")
XLA_COMPILE_SECONDS = REGISTRY.counter(
    "trino_xla_compile_seconds_total", "Cumulative XLA backend compile seconds")
JIT_CACHE_HITS = REGISTRY.counter(
    "trino_jit_cache_hits_total", "Executor jit-cache hits, by cache")
JIT_CACHE_MISSES = REGISTRY.counter(
    "trino_jit_cache_misses_total", "Executor jit-cache misses, by cache")
LISTENER_FAILURES = REGISTRY.counter(
    "trino_event_listener_failures_total", "EventListener callbacks that raised")
WORKER_TASKS = REGISTRY.counter(
    "trino_worker_tasks_total", "Stage tasks executed by this worker, by state")
CHAINS_BUILT = REGISTRY.counter(
    "trino_chains_built_total", "Fused operator chains built for jit compilation")
SCHED_ADMISSIONS = REGISTRY.counter(
    "trino_sched_admissions_total", "Fleet stage tasks admitted, by stage_admission mode")
SCHED_ADMISSION_WAIT = REGISTRY.histogram(
    "trino_sched_admission_wait_seconds", "Queue-to-first-dispatch wait per fleet task, by mode")
SCHED_OVERLAP = REGISTRY.gauge(
    "trino_sched_overlap_seconds", "Producer/consumer overlap won by pipelined admission, last fleet query")
SCHED_RESCINDS = REGISTRY.counter(
    "trino_sched_rescinds_total", "Pipelined admissions rescinded after a producer-attempt quarantine")
SHAPE_PAD_WASTE = REGISTRY.gauge(
    "trino_shape_bucket_pad_waste_ratio",
    "Fraction of bucketed capacity lost to padding, by bucketing site")
PERSISTENT_CACHE_DEGRADED = REGISTRY.gauge(
    "trino_persistent_cache_degraded",
    "1 when this process fell back to in-memory-only compilation after a wedged cache deserialize")
COMPILE_DESERIALIZE_FALLBACKS = REGISTRY.counter(
    "trino_compile_deserialize_fallbacks_total",
    "Compile-service watchdog trips: cache-backed compilations abandoned past the deadline")
PERSISTENT_CACHE_HITS = REGISTRY.counter(
    "trino_persistent_cache_hits_total",
    "XLA programs deserialized from the on-disk compilation cache instead of compiled")
DISPATCH_QUEUE_DEPTH = REGISTRY.gauge(
    "trino_dispatch_queue_depth",
    "Fleet slot requests waiting in the fair-share dispatch queue, by resource group")
SLOT_WAIT = REGISTRY.histogram(
    "trino_slot_wait_seconds",
    "Wait from slot request to fleet-slot grant under fair-share dispatch",
    # slot waits range from instant (idle fleet) to whole-query
    # runtimes under saturation — match the sched-admission spread
    buckets=(0.0005, 0.002, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
             5.0, 15.0, 60.0))
QUERIES_RUNNING = REGISTRY.gauge(
    "trino_queries_running",
    "Queries currently holding a running slot, by resource group")
QUERIES_QUEUED = REGISTRY.gauge(
    "trino_queries_queued",
    "Queries waiting in admission queues, by resource group")
SCAN_CACHE_HITS = REGISTRY.counter(
    "trino_scan_cache_hits_total",
    "Table-scan page materializations served from the shared scan-page cache")
SCAN_CACHE_MISSES = REGISTRY.counter(
    "trino_scan_cache_misses_total",
    "Table-scan page materializations that had to hit the connector")
RESULT_CACHE_HITS = REGISTRY.counter(
    "trino_result_cache_hits_total",
    "Statements served byte-identical from a semantic result cache")
RESULT_CACHE_MISSES = REGISTRY.counter(
    "trino_result_cache_misses_total",
    "Result-cache probes that fell through to execution")
RESULT_CACHE_BYTES = REGISTRY.gauge(
    "trino_result_cache_bytes",
    "Host bytes resident in semantic result caches")
DEVICE_CACHE_ENTRIES = REGISTRY.gauge(
    "trino_device_cache_entries",
    "Pages pinned in the HBM-resident device table cache")
DEVICE_CACHE_BYTES = REGISTRY.gauge(
    "trino_device_cache_bytes",
    "Device bytes pinned by the HBM-resident table cache")
DEVICE_CACHE_EVICTIONS = REGISTRY.counter(
    "trino_device_cache_evictions_total",
    "Device-cache entries evicted (LRU pressure or pool revocation)")
SCAN_ROWGROUPS_TOTAL = REGISTRY.counter(
    "trino_scan_rowgroups_total",
    "Storage row groups considered by split generation / pruned scans")
SCAN_ROWGROUPS_PRUNED = REGISTRY.counter(
    "trino_scan_rowgroups_pruned",
    "Row groups skipped by min/max footer-stat pruning")
SCAN_PARTITIONS_PRUNED = REGISTRY.counter(
    "trino_scan_partitions_pruned",
    "Hive-style partition directories skipped by partition-value pruning")
SCAN_BYTES_READ = REGISTRY.counter(
    "trino_scan_bytes_read",
    "Compressed storage bytes actually read from columnar files")
SCAN_BATCHES = REGISTRY.counter(
    "trino_scan_batches",
    "Row-group batches streamed through the out-of-core scan operator")
EXCHANGE_PARTITION_ROWS = REGISTRY.counter(
    "trino_exchange_partition_rows",
    "Rows routed to each output partition across exchange edges "
    "(spool boundary always; mesh all_to_all exactly when the "
    "exchange_partition_counters debug sync is on, or every Nth "
    "exchange under exchange_partition_counter_sample)")
EXCHANGE_PARTITION_BYTES = REGISTRY.counter(
    "trino_exchange_partition_bytes",
    "Encoded bytes routed to each output partition at the spool "
    "exchange boundary")
EXCHANGE_SALTED_ROWS = REGISTRY.counter(
    "trino_exchange_salted_rows_total",
    "Rows read through SALTED exchange edges (hot partitions fanned "
    "out across salt tasks), labelled fanout vs replicate")
ADAPTIVE_REPARTITIONS = REGISTRY.counter(
    "trino_adaptive_repartitions_total",
    "Stages whose output partition count was grown at runtime after "
    "an input edge blew past its cardinality estimate")
DIAG_BUNDLES = REGISTRY.counter(
    "trino_diag_bundles_total",
    "Post-mortem diagnostic bundles assembled, by trigger error class")
TIMESERIES_SAMPLES = REGISTRY.counter(
    "trino_timeseries_samples_total",
    "Cluster time-series scrape rounds recorded into the ring")
TIMESERIES_SCRAPE_FAILURES = REGISTRY.counter(
    "trino_timeseries_scrape_failures_total",
    "Worker /v1/metrics scrapes that failed during a time-series round")
PROGRAM_CATALOG_ENTRIES = REGISTRY.gauge(
    "trino_program_catalog_entries",
    "Compiled XLA programs currently retained in the program catalog")
PROGRAM_REGISTRATIONS = REGISTRY.counter(
    "trino_program_catalog_registrations_total",
    "Compiled programs registered in the catalog, by registering source")
PROGRAM_EVICTIONS = REGISTRY.counter(
    "trino_program_catalog_evictions_total",
    "Program-catalog entries evicted past the retention cap (LRU)")
MEMORY_ESTIMATE_RATIO = REGISTRY.gauge(
    "trino_memory_estimate_ratio",
    "memory_analysis() temp+output bytes over the MemoryContext "
    "reservation for the same query — the estimate-based governor's "
    "error, last measured query")
KERNEL_PROFILES = REGISTRY.counter(
    "trino_kernel_profiles_total",
    "Device profile captures taken by the kernel observatory, by trigger")
CLUSTER_WORKERS = REGISTRY.gauge(
    "trino_cluster_workers",
    "Workers currently registered with the membership layer, by "
    "lifecycle state (active / draining / inactive)")
DRAIN_DURATION = REGISTRY.histogram(
    "trino_drain_duration_seconds",
    "Graceful-drain wall time: POST /v1/drain to deregistration "
    "(running tasks finished AND every dependent consumer committed)",
    buckets=(0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0))
MEMBERSHIP_TRANSITIONS = REGISTRY.counter(
    "trino_membership_transitions_total",
    "Membership state-machine transitions, labelled from/to")
ORPHAN_TASKS_REAPED = REGISTRY.counter(
    "trino_orphan_tasks_reaped_total",
    "Worker tasks cancelled by the orphan reaper after their "
    "coordinator went silent past the liveness TTL")
EXCHANGE_BUFFER_ORPHAN_EVICTIONS = REGISTRY.counter(
    "trino_exchange_buffer_orphan_evictions_total",
    "Exchange-buffer entries released by the orphan reaper for "
    "queries whose coordinator stopped polling (memory that a dead "
    "coordinator would otherwise pin forever)")
JOURNAL_APPENDS = REGISTRY.counter(
    "trino_journal_appends_total",
    "Query-journal WAL records fsync'd, by record type")
QUERIES_RECOVERED = REGISTRY.counter(
    "trino_queries_recovered_total",
    "Journaled queries adopted by a restarted coordinator, by outcome "
    "(resumed / rehydrated / unresumable)")
JOURNAL_GC_REMOVED = REGISTRY.counter(
    "trino_journal_gc_removed_total",
    "Terminal query-journal entries removed by the tracker's periodic "
    "GC sweep (keeps _journal/ bounded across restarts)")
HISTORY_ENTRIES = REGISTRY.gauge(
    "trino_history_entries",
    "Completed-query records currently retained by the performance "
    "sentry's history store")
ANOMALIES = REGISTRY.counter(
    "trino_anomalies_total",
    "Completion-time anomaly verdicts emitted by the performance "
    "sentry, by driver bucket (xla_compile / scan / exchange / "
    "straggler_slack / cache_miss_expected_hit / ...)")
WRITE_ROWS = REGISTRY.counter(
    "trino_write_rows_total",
    "Rows appended through TableWriter sinks (counted at the writing "
    "task, before commit)")
WRITE_BYTES = REGISTRY.counter(
    "trino_write_bytes_total",
    "Bytes written by TableWriter sinks into staged / committed "
    "storage artifacts")
WRITE_FILES = REGISTRY.counter(
    "trino_write_files_total",
    "Storage files sealed by TableWriter sinks (parquet part files; "
    "memory fragments count as one each)")
WRITE_COMMIT_SECONDS = REGISTRY.histogram(
    "trino_write_commit_seconds",
    "TableFinish commit latency: Connector.finish_write wall time "
    "(CRC verify + atomic renames + manifest publish)",
    buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0))
PROCESS_RSS = REGISTRY.gauge(
    "trino_process_rss_bytes",
    "Resident set size of this node process")
PROCESS_OPEN_FDS = REGISTRY.gauge(
    "trino_process_open_fds",
    "Open file descriptors held by this node process")
PROCESS_THREADS = REGISTRY.gauge(
    "trino_process_threads",
    "Live Python threads in this node process")
PROCESS_UPTIME = REGISTRY.gauge(
    "trino_process_uptime_seconds",
    "Seconds since this node process imported the engine")
BUILD_INFO = REGISTRY.gauge(
    "trino_build_info",
    "Constant 1, labelled with the engine version and node role "
    "(info-style gauge)")

#: module-import timestamp — the uptime gauge's epoch
_PROCESS_START = time.time()


def _read_rss_bytes() -> int:
    """RSS from /proc (Linux); getrusage fallback elsewhere."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(rss_kb) * 1024
    except Exception:
        return 0


def refresh_process_gauges(node: str = "unknown") -> None:
    """Refresh the process-health gauge family (called by both node
    types' ``/v1/metrics`` handlers just before rendering, so scrapes
    always see current values without any background thread)."""
    PROCESS_RSS.set(_read_rss_bytes())
    try:
        PROCESS_OPEN_FDS.set(len(os.listdir("/proc/self/fd")))
    except OSError:
        pass
    PROCESS_THREADS.set(threading.active_count())
    PROCESS_UPTIME.set(time.time() - _PROCESS_START)
    try:
        from trino_tpu import __version__ as _version
    except Exception:
        _version = "unknown"
    BUILD_INFO.set(1, version=_version, node=node)


# ---------------------------------------------------------------------------
# XLA compile instrumentation
# ---------------------------------------------------------------------------

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_PCACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_hook_installed = False
_hook_lock = threading.Lock()
#: per-thread flag: a persistent-cache hit event precedes the
#: backend_compile_duration event of the SAME compile request (which,
#: on this jax version, fires for retrievals too — counting it as a
#: compile would make warm processes look cold)
_hook_tls = threading.local()


def install_jax_compile_hook() -> bool:
    """Register jax.monitoring listeners feeding the compile counters.

    ``trino_xla_compile_total`` counts REAL backend compiles only:
    ``backend_compile_duration`` fires for persistent-cache retrievals
    as well, so a preceding ``cache_hits`` event (same thread, same
    request) reroutes that sample to
    ``trino_persistent_cache_hits_total`` instead.

    Idempotent; returns True when the hook is (already) active. Uses the
    private ``jax._src.monitoring`` registration API (present on jax
    0.4.x); degrades to a no-op when unavailable.
    """
    global _hook_installed
    with _hook_lock:
        if _hook_installed:
            return True
        try:
            from jax._src import monitoring as _mon

            def _on_event(event: str, **kw: Any) -> None:
                if event == _PCACHE_HIT_EVENT:
                    _hook_tls.pcache_hit = True

            def _on_duration(event: str, duration: float, **kw: Any) -> None:
                if event == _COMPILE_EVENT:
                    if getattr(_hook_tls, "pcache_hit", False):
                        _hook_tls.pcache_hit = False
                        PERSISTENT_CACHE_HITS.inc()
                    else:
                        XLA_COMPILES.inc()
                        XLA_COMPILE_SECONDS.inc(duration)

            _mon.register_event_listener(_on_event)
            _mon.register_event_duration_secs_listener(_on_duration)
            _hook_installed = True
        except Exception:
            _hook_installed = False
        return _hook_installed


def compile_snapshot() -> Dict[str, float]:
    """Current compile/cache counter values (for before/after deltas)."""
    return {
        "compiles": XLA_COMPILES.total(),
        "compile_seconds": XLA_COMPILE_SECONDS.total(),
        "cache_hits": JIT_CACHE_HITS.total(),
        "cache_misses": JIT_CACHE_MISSES.total(),
        "persistent_hits": PERSISTENT_CACHE_HITS.total(),
    }


class CountingCache(dict):
    """A jit cache dict that counts hit/miss rates into the registry.

    Drop-in for the executors' ``self._jit_cache`` dicts: ``.get`` misses
    and ``__contains__`` checks that come up empty count as misses; the
    matching ``.get``/``[]`` that find an entry count as hits.
    """

    _MISS = object()

    def __init__(self, cache_name: str) -> None:
        super().__init__()
        self._cache_name = cache_name

    def get(self, key: Any, default: Any = None) -> Any:
        hit = dict.get(self, key, CountingCache._MISS)
        if hit is CountingCache._MISS:
            JIT_CACHE_MISSES.inc(cache=self._cache_name)
            return default
        JIT_CACHE_HITS.inc(cache=self._cache_name)
        return hit
