"""Coordinator: HTTP statement protocol + query lifecycle.

The analog of the reference's dispatch/protocol layer:

- ``POST /v1/statement`` submits SQL and returns the first protocol
  response with a ``nextUri`` (QueuedStatementResource.postStatement,
  MAIN/dispatcher/QueuedStatementResource.java:158);
- ``GET /v1/statement/executing/{id}/{slug}/{token}`` pages results
  (ExecutingStatementResource,
  MAIN/server/protocol/ExecutingStatementResource.java:71) — each
  response carries a batch of rows and the next token's URI until the
  query drains;
- ``DELETE`` on the same URI cancels
- ``GET /v1/info`` / ``GET /v1/queries`` expose server/query state
  (QueryResource analog, MAIN/server/QueryResource.java).

The lifecycle mirrors QueryStateMachine's QUEUED -> RUNNING ->
FINISHED/FAILED states (MAIN/execution/QueryStateMachine.java) with a
worker thread per query (dispatch is cheap here: the heavy lifting is
device execution, serialized through the engine's executor).
"""

from __future__ import annotations

import json
import secrets
import threading
import time
import traceback
import uuid
from dataclasses import dataclass, field
from decimal import Decimal
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from trino_tpu import session_properties as sp
from trino_tpu.engine import QueryResult, QueryRunner
from trino_tpu.tracker import QueryTracker

__all__ = ["Coordinator"]

#: rows per protocol page (the reference targets bytes; rows are fine
#: for a first protocol cut)
PAGE_ROWS = 4096

#: typed failures surface through /v1/statement with DISTINCT error
#: codes + names (the reference's StandardErrorCode registry,
#: SPI/StandardErrorCode.java) — a client can tell a reaped deadline
#: from an exhausted retry tier from a plain cancel without parsing
#: message prose. Code 1 = GENERIC_INTERNAL_ERROR fallback.
ERROR_CODES = {
    "QueryDeadlineExceededError": (131, "EXCEEDED_TIME_LIMIT"),
    "QueryRetriesExhaustedError": (132, "QUERY_RETRIES_EXHAUSTED"),
    "QueryCancelled": (130, "USER_CANCELED"),
    "ExceededMemoryLimitError": (133, "EXCEEDED_MEMORY_LIMIT"),
    "InsufficientResourcesError": (134, "INSUFFICIENT_RESOURCES"),
    # a restarted coordinator could not resume the query (not
    # journaled as fault-tolerant): the statement was fine, resubmit
    "CoordinatorRestartedError": (135, "COORDINATOR_RESTARTED"),
    # cluster-wide sliding-window retry budget spent (retry_budget)
    "RetryBudgetExhaustedError": (136, "RETRY_BUDGET_EXHAUSTED"),
}


def error_payload(error: str | None) -> dict:
    name = (error or "").split(":", 1)[0].strip()
    code, error_name = ERROR_CODES.get(
        name, (1, "GENERIC_INTERNAL_ERROR")
    )
    return {
        "message": error or "unknown error",
        "errorCode": code,
        "errorName": error_name,
    }


@dataclass
class QueryState:
    query_id: str
    slug: str
    sql: str
    state: str = "QUEUED"  # QUEUED | RUNNING | FINISHED | FAILED
    user: str = "user"
    resource_group: str = "global"
    result: QueryResult | None = None
    error: str | None = None
    error_detail: str | None = None  # server-side traceback
    created_at: float = field(default_factory=time.time)
    #: RUNNING transition time (execution-deadline epoch)
    started_at: float | None = None
    finished_at: float | None = None
    cancelled: bool = False
    #: cooperative cancellation signal checked by the executor
    cancel_event: object = field(default_factory=threading.Event)
    #: deadline limits captured from session properties at submit
    #: (0 = unlimited); the QueryTracker reaper enforces them
    max_queued_s: float = 0.0
    max_exec_s: float = 0.0


class Coordinator:
    """Embedded coordinator server (TestingTrinoServer analog,
    MAIN/server/testing/TestingTrinoServer.java:141)."""

    def __init__(
        self, runner: QueryRunner | None = None, port: int = 0,
        resource_groups=None, journal=None,
    ):
        from trino_tpu.server.resource_groups import ResourceGroupManager

        self.runner = runner or QueryRunner.tpch("tiny")
        #: durable query journal shared with a journal-wired fleet
        #: runner; recover() replays it, submit() WALs client records
        self.journal = journal or getattr(self.runner, "journal", None)
        self._queries: dict[str, QueryState] = {}
        self._lock = threading.Lock()
        #: query-state transitions notify this condition so protocol
        #: threads parked in page() wake immediately (the reference's
        #: asyncResponse completion, not a sleep-poll)
        self._state_cond = threading.Condition()
        self._seq = 0
        #: finished queries stay fetchable at least this long
        self.history_grace_s = 60.0
        #: admission control (InternalResourceGroupManager analog).
        #: A serving runner carries its own manager (fair-share weights
        #: feed fleet-slot dispatch) — adopt it so admission and slot
        #: scheduling read one group tree, like the reference where
        #: DispatchManager and the scheduler share one
        #: InternalResourceGroupManager.
        self.resource_groups = (
            resource_groups
            or getattr(self.runner, "resource_groups", None)
            or ResourceGroupManager()
        )
        #: cluster-wide memory view (ClusterMemoryManager analog): in
        #: the embedded single-node shape it observes the local pool
        #: after every statement; a serving/fleet-backed coordinator
        #: shares the runner's manager, which is fed worker snapshots
        from trino_tpu.memory import ClusterMemoryManager

        self.cluster_memory = (
            getattr(self.runner, "cluster_memory", None)
            or ClusterMemoryManager()
        )
        #: deadline governance: background reaper enforcing
        #: query_max_queued_time / query_max_execution_time
        #: (MAIN/execution/QueryTracker.java enforceTimeLimits analog)
        self.query_tracker = QueryTracker(self)
        #: cluster time-series recorder — constructed in start() ONLY
        #: when TRINO_TPU_TIMESERIES_INTERVAL_MS enables it (None =
        #: disabled = no background scrape thread exists at all)
        self.timeseries = None
        #: live cluster membership (elastic fleet): adopt the
        #: serving runner's registry when it wired one in, else own a
        #: fresh one — workers started with --coordinator PUT
        #: /v1/announce here either way
        from trino_tpu.membership import MembershipRegistry

        self.membership = (
            getattr(self.runner, "membership", None)
            or MembershipRegistry()
        )
        # system.runtime tables over live coordinator state
        # (MAIN/connector/system/ analog)
        from trino_tpu.connectors.system import SystemConnector

        self.runner.metadata.register_catalog(
            "system", SystemConnector(coordinator=self, runner=self.runner)
        )
        coordinator = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _send(self, code: int, payload: dict | None):
                if code == 204 or payload is None:
                    self.send_response(code)
                    self.end_headers()
                    return
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_PUT(self):
                path, _, _ = self.path.partition("?")
                if path != "/v1/announce":
                    self._send(404, {"error": "not found"})
                    return
                n = int(self.headers.get("Content-Length", "0"))
                try:
                    req = json.loads(self.rfile.read(n).decode())
                except (ValueError, UnicodeDecodeError):
                    self._send(400, {"error": "bad announce body"})
                    return
                node_id = str(req.get("node_id") or "").strip()
                uri = str(req.get("uri") or "").strip()
                if not node_id or not uri:
                    self._send(
                        400, {"error": "node_id and uri required"}
                    )
                    return
                self._send(200, coordinator.membership.announce(
                    node_id,
                    uri,
                    state=str(req.get("state") or "ACTIVE"),
                    active_tasks=int(req.get("active_tasks") or 0),
                ))

            def do_POST(self):
                path, _, query = self.path.partition("?")
                if path == "/v1/profile":
                    # kernel observatory: blocking device-profile
                    # capture on the coordinator process (local/mesh
                    # executors run in-process here)
                    from urllib.parse import parse_qs

                    from trino_tpu import kernel_profile

                    dur = (
                        parse_qs(query).get("duration_ms") or [500]
                    )[0]
                    try:
                        dur = float(dur)
                    except (TypeError, ValueError):
                        self._send(400, {"error": "bad duration_ms"})
                        return
                    out = kernel_profile.capture_for(
                        dur, trigger="endpoint"
                    )
                    self._send(200 if "error" not in out else 409, out)
                    return
                if self.path != "/v1/statement":
                    self._send(404, {"error": "not found"})
                    return
                n = int(self.headers.get("Content-Length", "0"))
                sql = self.rfile.read(n).decode()
                user = self.headers.get("X-Trino-User") or "user"
                q = coordinator.submit(sql, user=user)
                self._send(200, coordinator.proto_response(q, 0, self._base()))

            def do_GET(self):
                parts = self.path.strip("/").split("/")
                if self.path == "/v1/metrics":
                    # Prometheus text exposition (the reference's
                    # /v1/status JMX surface, flattened): query states,
                    # retry/speculation counters, memory gauges, RPC
                    # latency histograms
                    from trino_tpu import telemetry

                    telemetry.refresh_process_gauges(node="coordinator")
                    body = telemetry.REGISTRY.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if self.path == "/v1/info":
                    self._send(200, {
                        "nodeVersion": {"version": "trino-tpu-0.1"},
                        "coordinator": True,
                        "starting": False,
                    })
                    return
                if self.path == "/v1/queries":
                    self._send(200, coordinator.list_queries())
                    return
                if self.path == "/v1/query":
                    # live QueryInfo list (QueryResource analog): one
                    # light row per known query
                    self._send(200, coordinator.query_info_list())
                    return
                if self.path.split("?")[0] == "/v1/history":
                    # the performance sentry's durable query history
                    # (most-recent-last; ?limit=N bounds the tail)
                    from trino_tpu import history as history_mod

                    limit = None
                    if "?" in self.path:
                        from urllib.parse import parse_qs

                        qs = parse_qs(self.path.split("?", 1)[1])
                        if qs.get("limit"):
                            try:
                                limit = int(qs["limit"][0])
                            except ValueError:
                                limit = None
                    store = history_mod.active()
                    self._send(200, {
                        "entries": store.entries(limit=limit),
                        "total": len(store),
                        "durable": store.path is not None,
                    })
                    return
                if self.path == "/v1/anomalies":
                    # typed AnomalyVerdicts the sentry has emitted
                    from trino_tpu import sentry as sentry_mod

                    sen = sentry_mod.active()
                    self._send(200, {
                        "anomalies": [
                            v.to_dict() for v in sen.anomalies()
                        ],
                        "baselines": sen.baseline_count(),
                    })
                    return
                if self.path == "/v1/cluster/timeseries":
                    # the bounded metric ring the background recorder
                    # keeps (404 when time-series is disabled — no
                    # recorder means no thread AND no endpoint)
                    rec = coordinator.timeseries
                    if rec is None:
                        self._send(
                            404, {"error": "time-series disabled"}
                        )
                    else:
                        self._send(200, {
                            "interval_ms": rec.interval_ms,
                            "samples": rec.samples(),
                        })
                    return
                if (
                    len(parts) == 4
                    and parts[:2] == ["v1", "query"]
                    and parts[3] == "diagnostics"
                ):
                    # post-mortem bundle of a failed query (404 while
                    # it runs, succeeds, or after retention sweeps it)
                    from trino_tpu import tracker as _tracker

                    bundle = _tracker.QUERY_INFO.get_diagnostics(
                        parts[2]
                    )
                    if bundle is None:
                        self._send(
                            404, {"error": "no diagnostics bundle"}
                        )
                    else:
                        self._send(200, bundle)
                    return
                if len(parts) == 3 and parts[:2] == ["v1", "query"]:
                    # full stage -> task -> operator tree, served live
                    # while the query is still running
                    info = coordinator.query_info(parts[2])
                    if info is None:
                        self._send(404, {"error": "query not found"})
                    else:
                        self._send(200, info)
                    return
                if parts == ["v1", "programs"]:
                    # compiled-program catalog (kernel observatory):
                    # same payload system.runtime.programs serves
                    from trino_tpu import program_catalog

                    self._send(200, {
                        "programs": program_catalog.CATALOG.snapshot(),
                    })
                    return
                if (
                    len(parts) == 3
                    and parts[:2] == ["v1", "programs"]
                ):
                    from trino_tpu import program_catalog

                    e = program_catalog.CATALOG.get(parts[2])
                    if e is None:
                        self._send(404, {"error": "no such program"})
                    else:
                        self._send(200, e.to_dict(include_hlo=True))
                    return
                if (
                    len(parts) == 6
                    and parts[:3] == ["v1", "statement", "executing"]
                ):
                    _, _, _, qid, slug, token = parts
                    payload, code = coordinator.page(
                        qid, slug, int(token), self._base()
                    )
                    self._send(code, payload)
                    return
                self._send(404, {"error": "not found"})

            def do_DELETE(self):
                parts = self.path.strip("/").split("/")
                if (
                    len(parts) == 6
                    and parts[:3] == ["v1", "statement", "executing"]
                ):
                    coordinator.cancel(parts[3])
                    self._send(204, None)
                    return
                self._send(404, {"error": "not found"})

            def _base(self) -> str:
                host = self.headers.get("Host") or "localhost"
                return f"http://{host}"

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> "Coordinator":
        import os

        if os.environ.get("TRINO_TPU_PREWARM", "") not in ("", "0"):
            # trace-compile the canonical bucket set before serving
            # (persistent-cache-backed: warm machines deserialize
            # instead of compiling; off by default for fast test spins)
            from trino_tpu.exec import shapes

            shapes.prewarm()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        self.query_tracker.start()
        from trino_tpu import telemetry_analysis

        self.timeseries = telemetry_analysis.ClusterTimeseriesRecorder.from_env(
            # live-resolved so fleet worker eviction/readmission is
            # reflected scrape-to-scrape; a local runner has no workers
            lambda: [
                w.uri
                for w in getattr(self.runner, "workers", ()) or ()
                if getattr(w, "alive", True)
            ]
        )
        if self.timeseries is not None:
            self.timeseries.start()
            telemetry_analysis.set_active_recorder(self.timeseries)
        return self

    def stop(self):
        if self.timeseries is not None:
            from trino_tpu import telemetry_analysis

            self.timeseries.stop()
            if telemetry_analysis.active_recorder() is self.timeseries:
                telemetry_analysis.set_active_recorder(None)
            self.timeseries = None
        self.query_tracker.stop()
        self._httpd.shutdown()
        self._httpd.server_close()

    def recover(self) -> dict:
        """Replay the durable query journal after a restart — call
        between construction and :meth:`start` (connections arriving
        in between queue in the listen backlog, so clients never see
        a half-recovered coordinator).

        Per journaled query:

        - terminal (``done`` record): rehydrate its registry row —
          ``system.runtime.queries`` / ``GET /v1/query/{id}`` and any
          failure post-mortem bundle survive the restart, flagged
          ``recovered=true``. Result pages are NOT journaled, so the
          old protocol URI does not come back for finished queries.
        - RUNNING + fault-tolerant (``retry_policy`` TASK/QUERY with a
          spool epoch): re-registered at its OLD qid+slug protocol URI
          and resumed on a background thread — committed spool
          attempts are inherited, live worker attempts adopted, only
          the in-flight tail re-dispatched.
        - RUNNING but not resumable (retry_policy=NONE, or an
          unreadable journal): failed typed COORDINATOR_RESTARTED at
          its old URI; the statement was fine — resubmission is the
          client's remedy.

        Returns ``{"resumed": n, "rehydrated": n, "unresumable": n}``.
        """
        from trino_tpu import telemetry, tracker

        counts = {"resumed": 0, "rehydrated": 0, "unresumable": 0}
        if self.journal is None:
            return counts
        to_resume = []
        for e in self.journal.scan():
            if e.done is not None:
                tracker.QUERY_INFO.rehydrate(
                    e.query_id,
                    state=e.done.get("state", "FINISHED"),
                    sql=e.sql,
                    user=(e.begin or e.client or {}).get("user"),
                    rows=e.done.get("rows"),
                    error=e.done.get("error"),
                    elapsed_ms=e.done.get("elapsed_ms", 0.0),
                    diagnostics=e.done.get("diagnostics"),
                )
                counts["rehydrated"] += 1
                telemetry.QUERIES_RECOVERED.inc(outcome="rehydrated")
                continue
            q = QueryState(
                query_id=e.query_id,
                slug=(e.client or {}).get("slug") or secrets.token_hex(8),
                sql=e.sql or "",
                user=str((e.begin or e.client or {}).get("user") or "user"),
            )
            tracker.QUERY_INFO.mark_recovered(e.query_id)
            if e.resumable and hasattr(self.runner, "resume"):
                with self._lock:
                    self._queries[e.query_id] = q
                to_resume.append((q, e))
                counts["resumed"] += 1
                telemetry.QUERIES_RECOVERED.inc(outcome="resumed")
            else:
                q.state = "FAILED"
                q.error = (
                    "CoordinatorRestartedError: the coordinator "
                    "restarted and cannot resume this query "
                    f"(retry_policy="
                    f"{(e.begin or {}).get('retry_policy', 'NONE')}); "
                    "resubmit the statement"
                )
                q.finished_at = time.time()
                with self._lock:
                    self._queries[e.query_id] = q
                tracker.QUERY_INFO.rehydrate(
                    e.query_id, state="FAILED", sql=q.sql, user=q.user,
                    error=q.error,
                )
                try:
                    # terminal WAL record: the NEXT restart rehydrates
                    # this as history instead of re-failing it
                    self.journal.finish(
                        e.query_id, state="FAILED", error=q.error,
                    )
                except Exception:
                    pass
                counts["unresumable"] += 1
                telemetry.QUERIES_RECOVERED.inc(outcome="unresumable")

        def run_resumes():
            # sequential: the fleet runner executes one statement at a
            # time; clients long-poll their old URIs meanwhile
            for q, e in to_resume:
                q.state = "RUNNING"
                q.started_at = time.time()
                self._signal_state()
                try:
                    result = self.runner.resume(e)
                    q.result = result
                    q.state = "FINISHED"
                except Exception as exc:
                    if q.error is None:
                        q.error = f"{type(exc).__name__}: {exc}"
                        q.error_detail = traceback.format_exc()
                    q.state = "FAILED"
                q.finished_at = time.time()
                self._signal_state()

        if to_resume:
            threading.Thread(
                target=run_resumes, name="journal-resume", daemon=True,
            ).start()
        return counts

    @property
    def uri(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def _signal_state(self) -> None:
        """Wake every protocol thread blocked in ``page()``. Called on
        every query-state transition (run(), cancel(), the reaper)."""
        with self._state_cond:
            self._state_cond.notify_all()

    # ---- query management ------------------------------------------------

    def submit(self, sql: str, user: str = "user") -> QueryState:
        from trino_tpu.server.resource_groups import (
            QueryQueueFullError,
            QueryRejectedError,
        )

        with self._lock:
            self._seq += 1
            qid = f"{time.strftime('%Y%m%d_%H%M%S')}_{self._seq:05d}_{uuid.uuid4().hex[:5]}"
        q = QueryState(
            query_id=qid, slug=secrets.token_hex(8), sql=sql, user=user,
        )
        if self.journal is not None:
            # WAL the protocol identity (qid + slug) so a restarted
            # coordinator can re-serve this query at its old
            # /v1/statement/executing/{qid}/{slug}/{token} URI.
            # Best-effort: an unjournalable query still runs — it just
            # cannot survive a restart (the fleet's own begin/epoch
            # appends are the hard chaos seam).
            try:
                self.journal.note_client(qid, q.slug, user, sql)
            except Exception:
                pass
        # capture deadline limits at submit time so the reaper enforces
        # the session the query was dispatched under, not whatever the
        # session mutates to later
        q.max_queued_s = sp.parse_duration(
            sp.get(self.runner.session, "query_max_queued_time")
        )
        q.max_exec_s = sp.parse_duration(
            sp.get(self.runner.session, "query_max_execution_time")
        )
        # admission (resource groups): selection + queue-full fail-fast
        # happen BEFORE the dispatch thread exists (DispatchManager ->
        # resource-group queueing, MAIN/dispatcher/DispatchManager.java:146)
        try:
            group = self.resource_groups.select(user)
            q.resource_group = group.name
            admitted = self.resource_groups.enqueue(group, qid)
        except (QueryQueueFullError, QueryRejectedError) as e:
            q.state = "FAILED"
            q.error = f"{type(e).__name__}: {e}"
            q.finished_at = time.time()
            with self._lock:
                self._queries[qid] = q
            return q
        with self._lock:
            self._queries[qid] = q
            # bounded history: release old finished results (the
            # reference's QueryTracker min-age expiration,
            # MAIN/execution/QueryTracker.java). A grace period keeps a
            # finished query alive while a slow client is still
            # paginating its resultset — evicting it mid-pagination
            # would surface a spurious 404.
            if len(self._queries) > 200:
                now = time.time()
                done = [
                    k for k, v in self._queries.items()
                    if v.state in ("FINISHED", "FAILED")
                    and v.finished_at is not None
                    and now - v.finished_at > self.history_grace_s
                ]
                for k in done[: len(self._queries) - 200]:
                    del self._queries[k]
            if len(self._queries) > 2000:
                # hard bound: under burst load the grace period alone
                # would let resultset-holding entries grow unboundedly;
                # evict oldest finished regardless of age
                done = sorted(
                    (
                        k for k, v in self._queries.items()
                        if v.finished_at is not None
                    ),
                    key=lambda k: self._queries[k].finished_at,
                )
                for k in done[: len(self._queries) - 2000]:
                    del self._queries[k]

        def run():
            # wait for a running slot (FIFO within the group; immediate
            # when admission already granted one at submit)
            if not self.resource_groups.acquire(
                group, qid, lambda: q.cancelled, admitted=admitted
            ):
                # the reaper (queued-deadline) and DELETE both set
                # cancelled — keep whichever typed error got there first
                q.state = "FAILED"
                if q.error is None:
                    q.error = "Query was canceled while queued"
                q.finished_at = time.time()
                self._signal_state()
                return
            try:
                if q.cancelled:
                    q.state = "FAILED"
                    if q.error is None:
                        q.error = "Query was canceled while queued"
                    q.finished_at = time.time()
                    self._signal_state()
                    return
                q.state = "RUNNING"
                q.started_at = time.time()
                self._signal_state()
                try:
                    # cooperative cancellation: DELETE sets the event
                    # and the executor aborts at its next boundary
                    # the coordinator's id IS the runner's id: live
                    # QueryInfo published under it joins QueryState
                    # (tests substitute runners whose execute() has no
                    # query_id parameter — probe before passing it;
                    # same probe for user=, which a serving runner
                    # consumes for per-identity group selection)
                    kwargs = {"cancel_event": q.cancel_event}
                    try:
                        import inspect

                        params = inspect.signature(
                            self.runner.execute
                        ).parameters
                        if "query_id" in params:
                            kwargs["query_id"] = q.query_id
                        if "user" in params:
                            kwargs["user"] = q.user
                        # this thread already holds a resource-group
                        # running slot (acquired above, same adopted
                        # manager) — a serving runner must not gate a
                        # second time
                        if "admitted" in params:
                            kwargs["admitted"] = True
                    except (TypeError, ValueError):
                        pass
                    result = self.runner.execute(sql, **kwargs)
                    if q.cancelled or q.state == "FAILED":
                        q.state = "FAILED"
                    else:
                        q.result = result
                        q.state = "FINISHED"
                except Exception as e:  # surfaces through the protocol
                    # never clobber a reaper-set typed deadline error
                    # with the generic unwind exception it provoked
                    if q.error is None:
                        q.error = f"{type(e).__name__}: {e}"
                        q.error_detail = traceback.format_exc()
                    q.state = "FAILED"
                    q.result = None
                # a FleetRunner-backed coordinator has no local
                # executor; its pools arrive via task-status snapshots
                pool = getattr(
                    getattr(self.runner, "executor", None),
                    "memory_pool", None,
                )
                if pool is not None:
                    self.cluster_memory.observe(
                        pool.node_id, pool.snapshot()
                    )
                if q.finished_at is None:
                    q.finished_at = time.time()
            finally:
                self.resource_groups.release(group)
                self._signal_state()

        threading.Thread(target=run, daemon=True).start()
        return q

    def cancel(self, qid: str):
        q = self._queries.get(qid)
        if q is not None:
            q.cancelled = True
            q.cancel_event.set()
            if q.state in ("QUEUED", "RUNNING"):
                q.state = "FAILED"
                if q.error is None:
                    q.error = "QueryCancelled: Query was canceled"
                q.finished_at = time.time()
            # a queued query's dispatch thread is parked on the
            # resource-group condition variable — poke it so the cancel
            # takes effect now, not at the next poll tick
            self.resource_groups.wakeup()
            self._signal_state()

    def query_info_list(self) -> list[dict]:
        """``GET /v1/query``: one light row per known query, joining
        coordinator lifecycle state with the live registry's runtime
        stats (rows, peak memory). Queries executed through a runner
        directly (no QueryState) still appear from the registry."""
        from trino_tpu import tracker

        live = {r["query_id"]: r for r in tracker.QUERY_INFO.list()}
        with self._lock:
            snapshot = list(self._queries.values())
        out = []
        for q in snapshot:
            r = live.pop(q.query_id, None) or {}
            # time spent QUEUED: until the RUNNING transition, or (for
            # queries that died in the queue) until the terminal time;
            # still-QUEUED queries report a live, growing value
            queued_end = q.started_at or q.finished_at or time.time()
            out.append({
                "query_id": q.query_id,
                "state": q.state,
                "user": q.user,
                "query": q.sql,
                "resource_group": q.resource_group,
                "elapsed_ms": round(
                    ((q.finished_at or time.time()) - q.created_at)
                    * 1e3, 3,
                ),
                "queued_time_ms": round(
                    (queued_end - q.created_at) * 1e3, 3
                ),
                "peak_memory_bytes": r.get("peak_memory_bytes", 0),
                "rows": r.get("rows"),
                "error": q.error,
            })
        out.extend(live.values())
        return out

    def query_info(self, qid: str) -> dict | None:
        """``GET /v1/query/{id}``: the full stage → task → operator
        JSON tree. Coordinator lifecycle state overrides the
        registry's (it is authoritative for QUEUED/cancel races)."""
        from trino_tpu import tracker

        info = tracker.QUERY_INFO.get(qid)
        q = self._queries.get(qid)
        if info is None and q is None:
            return None
        if info is None:
            info = {
                "query_id": qid, "state": q.state, "user": q.user,
                "sql": q.sql, "elapsed_ms": round(
                    ((q.finished_at or time.time()) - q.created_at)
                    * 1e3, 3,
                ),
                "peak_memory_bytes": 0, "rows": None,
                "error": q.error, "stages": [],
            }
        elif q is not None:
            info["state"] = q.state
            info["user"] = q.user
            if q.error:
                info["error"] = q.error
        if q is not None:
            info["resource_group"] = q.resource_group
            info["queued_time_ms"] = round(
                ((q.started_at or q.finished_at or time.time())
                 - q.created_at) * 1e3, 3,
            )
        return info

    def list_queries(self) -> list[dict]:
        with self._lock:
            snapshot = list(self._queries.values())
        return [
            {
                "queryId": q.query_id,
                "state": q.state,
                "query": q.sql,
                "user": q.user,
                "resourceGroup": q.resource_group,
                "error": q.error,
                "errorDetail": q.error_detail,
            }
            for q in snapshot
        ]

    # ---- protocol responses ----------------------------------------------

    def page(self, qid: str, slug: str, token: int, base: str):
        q = self._queries.get(qid)
        if q is None or q.slug != slug:
            return {"error": "query not found"}, 404
        # long-poll: wait server-side for a state transition like the
        # reference's asyncResponse (ExecutingStatementResource). The
        # condition is notified by run()/cancel()/the reaper, so a
        # finishing query releases its waiting client immediately —
        # under high concurrency the old 10 ms sleep-poll added a
        # half-tick of latency per page to every client.
        deadline = time.time() + 1.0
        with self._state_cond:
            while q.state in ("QUEUED", "RUNNING"):
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._state_cond.wait(timeout=remaining)
        return self.proto_response(q, token, base), 200

    def proto_response(self, q: QueryState, token: int, base: str) -> dict:
        uri = f"{base}/v1/statement/executing/{q.query_id}/{q.slug}"
        resp = {
            "id": q.query_id,
            "infoUri": f"{base}/v1/queries",
            "stats": {
                "state": q.state,
                "queued": q.state == "QUEUED",
                "elapsedTimeMillis": int(
                    ((q.finished_at or time.time()) - q.created_at) * 1e3
                ),
            },
        }
        if q.state == "FAILED":
            resp["error"] = error_payload(q.error)
            return resp
        if q.state in ("QUEUED", "RUNNING") or q.result is None:
            resp["nextUri"] = f"{uri}/{token}"
            return resp
        result = q.result
        lo = token * PAGE_ROWS
        hi = lo + PAGE_ROWS
        resp["columns"] = [
            {"name": n, "type": _proto_type(result, i)}
            for i, n in enumerate(result.names)
        ]
        chunk = result.rows[lo:hi]
        if chunk:
            resp["data"] = [[_json_value(v) for v in row] for row in chunk]
        if hi < len(result.rows):
            resp["nextUri"] = f"{uri}/{token + 1}"
        return resp


def _proto_type(result: QueryResult, i: int) -> str:
    if result.plan is not None and i < len(result.plan.outputs):
        t = list(result.plan.outputs.values())[i]
        return str(t)
    # metadata statements carry strings/ints only
    for row in result.rows:
        v = row[i]
        if v is not None:
            if isinstance(v, bool):
                return "boolean"
            if isinstance(v, int):
                return "bigint"
            if isinstance(v, float):
                return "double"
            break
    return "varchar"


def _json_value(v):
    if isinstance(v, Decimal):
        return str(v)
    return v


def main():
    """Standalone coordinator daemon (``python -m trino_tpu.server.
    coordinator``): a fleet-backed coordinator with the durable query
    journal wired in. On startup it replays the journal — so a
    ``kill -9`` + restart with the SAME --spool resumes journaled
    FTE queries at their old protocol URIs. The recovery chaos
    harness and the recovery-smoke CI job drive exactly this entry
    point."""
    import argparse
    import os
    import sys

    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8090)
    ap.add_argument(
        "--workers", default="",
        help="comma-separated worker base URIs (fleet mode; omit for "
             "a local embedded runner)",
    )
    ap.add_argument(
        "--spool", default=None,
        help="spool root directory; fleet mode stores the durable "
             "query journal under it (_journal/)",
    )
    ap.add_argument("--catalog", default="tpch")
    ap.add_argument("--schema", default="tiny")
    ap.add_argument("--n-partitions", type=int, default=4)
    ap.add_argument(
        "--session", action="append", default=[], metavar="K=V",
        help="session property override (repeatable)",
    )
    args = ap.parse_args()
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    journal = None
    if args.workers:
        from trino_tpu.connectors.tpch.connector import TpchConnector
        from trino_tpu.journal import QueryJournal
        from trino_tpu.metadata import Metadata, Session
        from trino_tpu.server.fleet import FleetRunner

        md = Metadata()
        if args.catalog == "tpcds":
            from trino_tpu.connectors.tpcds.connector import (
                TpcdsConnector,
            )

            md.register_catalog("tpcds", TpcdsConnector())
        else:
            md.register_catalog("tpch", TpchConnector())
        session = Session(catalog=args.catalog, schema=args.schema)
        for kv in args.session:
            k, _, v = kv.partition("=")
            sp.set_property(session, k.strip(), v.strip())
        spool_root = args.spool or os.path.join(
            os.getcwd(), "trino_tpu_spool"
        )
        os.makedirs(spool_root, exist_ok=True)
        journal = QueryJournal(spool_root)
        runner = FleetRunner(
            [u.strip() for u in args.workers.split(",") if u.strip()],
            md, session, spool_root=spool_root,
            n_partitions=args.n_partitions, journal=journal,
        )
    else:
        runner = QueryRunner.tpch(args.schema)
    coord = Coordinator(runner, port=args.port, journal=journal)
    if journal is not None:
        # replay BEFORE serving: clients connecting during recovery
        # queue in the listen backlog and see a consistent view
        counts = coord.recover()
        print(f"recovery: {counts}", flush=True)
    coord.start()
    print(f"coordinator ready on port {coord.port}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        coord.stop()
        sys.exit(0)


if __name__ == "__main__":
    main()
