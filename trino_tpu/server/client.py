"""REST statement client.

The analog of the reference's StatementClientV1
(client/trino-client/.../StatementClientV1.java:68): POST the SQL,
then follow ``nextUri`` until it disappears, accumulating data pages.
Pure stdlib (urllib) — the server is localhost/cluster-internal.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

__all__ = ["StatementClient", "QueryError"]


class QueryError(RuntimeError):
    pass


class StatementClient:
    def __init__(self, server: str, timeout: float = 300.0):
        self.server = server.rstrip("/")
        self.timeout = timeout

    def _request(self, method: str, url: str, body: bytes | None = None) -> dict:
        req = urllib.request.Request(url, data=body, method=method)
        req.add_header("X-Trino-User", "user")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = e.read().decode()[:200]
            except Exception:
                pass
            raise QueryError(f"HTTP {e.code} from {url}: {detail}") from e
        except urllib.error.URLError as e:
            raise QueryError(f"cannot reach {url}: {e.reason}") from e
        return json.loads(payload) if payload else {}

    def execute(self, sql: str):
        """Run one statement; returns (columns, rows).

        ``columns`` is a list of {name, type} dicts; rows are lists of
        JSON-decoded values.
        """
        resp = self._request(
            "POST", f"{self.server}/v1/statement", sql.encode()
        )
        columns = None
        rows: list[list] = []
        deadline = time.time() + self.timeout
        while True:
            if "error" in resp:
                raise QueryError(resp["error"].get("message", "query failed"))
            if resp.get("columns") and columns is None:
                columns = resp["columns"]
            rows.extend(resp.get("data") or [])
            nxt = resp.get("nextUri")
            if nxt is None:
                break
            if time.time() > deadline:
                raise QueryError("client timeout")
            resp = self._request("GET", nxt)
        return columns or [], rows

    def server_info(self) -> dict:
        return self._request("GET", f"{self.server}/v1/info")

    def queries(self) -> list[dict]:
        return self._request("GET", f"{self.server}/v1/queries")
