"""REST statement client.

The analog of the reference's StatementClientV1
(client/trino-client/.../StatementClientV1.java:68): POST the SQL,
then follow ``nextUri`` until it disappears, accumulating data pages.
Pure stdlib (urllib) — the server is localhost/cluster-internal.

Transport-retry policy (the reference's OkHttp retry interceptor,
client/trino-client/.../StatementClientV1.java advance()): only
idempotent pagination GETs are retried, and only on transport faults
(connection refused/reset, HTTP 5xx). The submitting POST is never
retried — a retried POST could double-submit a statement — and
semantic query failures (an ``error`` payload in a 200 response)
always fail fast.
"""

from __future__ import annotations

import http.client
import json
import random
import time
import urllib.error
import urllib.request

__all__ = ["StatementClient", "QueryError"]


class QueryError(RuntimeError):
    """Statement failed. Carries the coordinator's typed error code /
    name when the failure came through the protocol's error payload
    (``errorCode``/``errorName``), else code 0 / None for client-side
    transport failures."""

    def __init__(self, message: str, error_code: int = 0,
                 error_name: str | None = None):
        super().__init__(message)
        self.error_code = error_code
        self.error_name = error_name


class StatementClient:
    #: transport retries per pagination GET (jittered exponential
    #: backoff); POSTs are never retried
    get_retries = 3
    #: base backoff in seconds; attempt k sleeps uniform(0, base * 2^k)
    retry_backoff_s = 0.05
    #: cap on any single backoff sleep — restart waits poll steadily
    #: instead of backing off into multi-minute gaps
    retry_sleep_cap_s = 2.0

    def __init__(self, server: str, timeout: float = 300.0,
                 restart_wait_s: float = 0.0):
        self.server = server.rstrip("/")
        self.timeout = timeout
        #: coordinator-restart tolerance: when > 0, pagination GETs
        #: keep retrying transport faults (connection refused while
        #: the coordinator is down, 404 while it replays the journal)
        #: until this much wall time has passed — a restarted
        #: coordinator re-serves journaled queries at their old
        #: nextUri, so the same client rides through the crash
        self.restart_wait_s = restart_wait_s
        self._rng = random.Random()

    def _request_once(
        self, method: str, url: str, body: bytes | None = None
    ) -> dict:
        req = urllib.request.Request(url, data=body, method=method)
        req.add_header("X-Trino-User", "user")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            detail = ""
            try:
                detail = e.read().decode()[:200]
            except Exception:
                pass
            err = QueryError(f"HTTP {e.code} from {url}: {detail}")
            err.http_status = e.code
            err.retryable = e.code >= 500
            raise err from e
        except urllib.error.URLError as e:
            err = QueryError(f"cannot reach {url}: {e.reason}")
            err.retryable = True
            raise err from e
        except (OSError, http.client.HTTPException) as e:
            # a server killed mid-response surfaces raw from read()
            # (RemoteDisconnected, IncompleteRead, reset) — the same
            # transport-fault class as a refused connection
            err = QueryError(f"transport failure from {url}: {e}")
            err.retryable = True
            raise err from e
        return json.loads(payload) if payload else {}

    def _request(
        self, method: str, url: str, body: bytes | None = None
    ) -> dict:
        retries = self.get_retries if method == "GET" else 0
        restart_deadline = (
            time.monotonic() + self.restart_wait_s
            if (self.restart_wait_s > 0 and method == "GET")
            else None
        )
        attempt = 0
        while True:
            try:
                return self._request_once(method, url, body)
            except QueryError as e:
                retryable = getattr(e, "retryable", False)
                if restart_deadline is not None:
                    # restart-wait mode: a brief 404 also rides — the
                    # coordinator may be back up but still replaying
                    # its journal when the GET lands
                    retryable = retryable or (
                        getattr(e, "http_status", 0) == 404
                    )
                    if retryable and time.monotonic() < restart_deadline:
                        time.sleep(min(
                            self.retry_sleep_cap_s,
                            self._rng.uniform(
                                0.0,
                                self.retry_backoff_s * (2 ** attempt),
                            ),
                        ))
                        attempt = min(attempt + 1, 16)
                        continue
                    raise
                if attempt >= retries or not retryable:
                    raise
                time.sleep(self._rng.uniform(
                    0.0, self.retry_backoff_s * (2 ** attempt)
                ))
                attempt += 1

    def execute(self, sql: str):
        """Run one statement; returns (columns, rows).

        ``columns`` is a list of {name, type} dicts; rows are lists of
        JSON-decoded values.
        """
        resp = self._request(
            "POST", f"{self.server}/v1/statement", sql.encode()
        )
        columns = None
        rows: list[list] = []
        deadline = time.time() + self.timeout
        while True:
            if "error" in resp:
                err = resp["error"]
                raise QueryError(
                    err.get("message", "query failed"),
                    error_code=int(err.get("errorCode", 0) or 0),
                    error_name=err.get("errorName"),
                )
            if resp.get("columns") and columns is None:
                columns = resp["columns"]
            rows.extend(resp.get("data") or [])
            nxt = resp.get("nextUri")
            if nxt is None:
                break
            if time.time() > deadline:
                raise QueryError("client timeout")
            resp = self._request("GET", nxt)
        return columns or [], rows

    def server_info(self) -> dict:
        return self._request("GET", f"{self.server}/v1/info")

    def queries(self) -> list[dict]:
        return self._request("GET", f"{self.server}/v1/queries")
