"""Resource groups: admission control ahead of dispatch.

The analog of the reference's InternalResourceGroupManager /
InternalResourceGroup tree (MAIN/execution/resourcegroups/): queries
select a group by identity, each group bounds concurrently-RUNNING and
QUEUED queries, admission is FIFO within a group, and over-limit
submissions fail fast with the reference's QUERY_QUEUE_FULL behavior.
Kept one level deep (no sub-group tree) and fair-share only — the
knobs that matter for a single-runner coordinator.
"""

from __future__ import annotations

import fnmatch
import threading
from collections import deque
from dataclasses import dataclass, field

__all__ = [
    "ResourceGroup", "ResourceGroupManager", "QueryQueueFullError",
    "QueryRejectedError",
]


class QueryQueueFullError(RuntimeError):
    """Too many queued queries for the selected group
    (QUERY_QUEUE_FULL analog — retryable)."""


class QueryRejectedError(RuntimeError):
    """No resource group matches the identity (QUERY_REJECTED analog —
    a configuration condition, not a capacity one)."""


@dataclass
class ResourceGroup:
    """One group's limits + its user selector (the resource-group
    config file's matching rules, plugin/trino-resource-group-managers)."""

    name: str
    max_running: int = 8
    max_queued: int = 100
    user: str = "*"
    #: fair-share weight for cluster-slot dispatch (the reference's
    #: schedulingWeight): when queries from several groups contend for
    #: fleet worker slots, grants are dealt deficit-round-robin in
    #: proportion to group weights — a weight-8 group gets ~8 slots
    #: for every 1 a weight-1 group gets, but the low-weight group is
    #: never starved (every group is visited each DRR round)
    weight: int = 1

    def __post_init__(self):
        if self.weight < 1:
            raise ValueError(
                f"resource group {self.name!r}: weight must be >= 1"
            )

    def matches(self, user: str) -> bool:
        return fnmatch.fnmatchcase(user, self.user)


class _GroupState:
    __slots__ = ("running", "queue")

    def __init__(self):
        self.running = 0
        self.queue: deque[str] = deque()


@dataclass
class ResourceGroupManager:
    """First-match-wins group selection + per-group FIFO admission."""

    groups: list[ResourceGroup] = field(
        default_factory=lambda: [ResourceGroup("global")]
    )

    def __post_init__(self):
        self._cond = threading.Condition()
        self._state = {g.name: _GroupState() for g in self.groups}
        self._publish()

    def _publish(self) -> None:
        """Export per-group running/queued counts as gauges (call with
        ``_cond`` held or before threads exist). One writer: admission
        state lives here, so the gauges can never disagree with it."""
        from trino_tpu import telemetry

        for g in self.groups:
            st = self._state[g.name]
            telemetry.QUERIES_RUNNING.set(st.running, group=g.name)
            telemetry.QUERIES_QUEUED.set(len(st.queue), group=g.name)

    def select(self, user: str) -> ResourceGroup:
        for g in self.groups:
            if g.matches(user):
                return g
        raise QueryRejectedError(
            f"no resource group matches user {user!r}"
        )

    def enqueue(self, group: ResourceGroup, qid: str) -> bool:
        """Admit at submit time: straight to RUNNING when a slot is
        free and nothing queues ahead (so max_queued only ever counts
        queries that genuinely cannot run — the reference's semantics),
        else into the FIFO queue, else fail fast when the queue is
        full. Returns True when admitted directly to running."""
        with self._cond:
            st = self._state[group.name]
            if not st.queue and st.running < group.max_running:
                st.running += 1
                self._publish()
                return True
            if len(st.queue) >= group.max_queued:
                raise QueryQueueFullError(
                    f"Too many queued queries for {group.name!r} "
                    f"(max {group.max_queued})"
                )
            st.queue.append(qid)
            self._publish()
            return False

    def acquire(
        self, group: ResourceGroup, qid: str, cancelled,
        admitted: bool = False,
    ) -> bool:
        """Block until ``qid`` reaches the queue head AND a running
        slot frees (FIFO fairness); immediate when enqueue() already
        admitted it. Returns False if cancelled while queued."""
        if admitted:
            return True
        with self._cond:
            st = self._state[group.name]
            while True:
                if cancelled():
                    try:
                        st.queue.remove(qid)
                    except ValueError:
                        pass
                    self._publish()
                    self._cond.notify_all()
                    return False
                if (
                    st.queue
                    and st.queue[0] == qid
                    and st.running < group.max_running
                ):
                    st.queue.popleft()
                    st.running += 1
                    self._publish()
                    self._cond.notify_all()
                    return True
                # long timeout: cancellation/reaping promptness comes
                # from wakeup(), not from busy-polling this wait
                self._cond.wait(timeout=1.0)

    def wakeup(self) -> None:
        """Nudge every thread parked in ``acquire``. Called by the
        coordinator when a queued query is cancelled or reaped so its
        dispatch thread re-checks ``cancelled()`` immediately instead
        of at the next wait timeout."""
        with self._cond:
            self._cond.notify_all()

    def release(self, group: ResourceGroup) -> None:
        with self._cond:
            st = self._state[group.name]
            st.running = max(st.running - 1, 0)
            self._publish()
            self._cond.notify_all()

    def stats(self) -> dict:
        """name -> {running, queued, max_running, max_queued, weight}
        (the resource-group JMX/system-table view)."""
        with self._cond:
            return {
                g.name: {
                    "running": self._state[g.name].running,
                    "queued": len(self._state[g.name].queue),
                    "max_running": g.max_running,
                    "max_queued": g.max_queued,
                    "weight": g.weight,
                }
                for g in self.groups
            }
