"""Coordinator HTTP server, REST client, and CLI.

The analog of the reference's client protocol stack: the coordinator
statement resources (MAIN/dispatcher/QueuedStatementResource.java:105,
MAIN/server/protocol/ExecutingStatementResource.java:71), the Java
client (client/trino-client/.../StatementClientV1.java:68), and the
terminal CLI (client/trino-cli/.../Console.java:86).
"""

from trino_tpu.server.coordinator import Coordinator
from trino_tpu.server.client import StatementClient

__all__ = ["Coordinator", "StatementClient"]
