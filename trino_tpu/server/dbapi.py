"""PEP 249 (DB-API 2.0) driver over the REST statement protocol.

The ecosystem-native analog of the reference's JDBC driver
(client/trino-jdbc/, TrinoConnection/TrinoResultSet wrapping
trino-client): a `connect()` returning Connection/Cursor objects any
Python SQL tooling can drive, wrapping StatementClient the same way.

    import trino_tpu.server.dbapi as dbapi
    conn = dbapi.connect("http://127.0.0.1:8080")
    cur = conn.cursor()
    cur.execute("select count(*) from nation")
    print(cur.fetchall())
"""

from __future__ import annotations

from trino_tpu.server.client import QueryError, StatementClient

apilevel = "2.0"
threadsafety = 1  # threads may share the module, not connections
paramstyle = "qmark"

__all__ = [
    "connect", "Connection", "Cursor",
    "Warning", "Error", "InterfaceError", "DatabaseError", "DataError",
    "OperationalError", "IntegrityError", "InternalError",
    "ProgrammingError", "NotSupportedError",
    "apilevel", "threadsafety", "paramstyle",
]


class Warning(Exception):  # noqa: A001 — PEP 249 name
    pass


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class DataError(DatabaseError):
    pass


class OperationalError(DatabaseError):
    pass


class IntegrityError(DatabaseError):
    pass


class InternalError(DatabaseError):
    pass


class ProgrammingError(DatabaseError):
    pass


class NotSupportedError(DatabaseError):
    pass


def connect(server: str, timeout: float = 300.0) -> "Connection":
    return Connection(server, timeout)


class Connection:
    def __init__(self, server: str, timeout: float = 300.0):
        self._client = StatementClient(server, timeout=timeout)
        self._closed = False

    def cursor(self) -> "Cursor":
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self._client)

    def close(self):
        self._closed = True

    # queries auto-commit (the engine's per-statement transaction)
    def commit(self):
        pass

    def rollback(self):
        raise NotSupportedError("rollback is not supported")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Cursor:
    arraysize = 1

    def __init__(self, client: StatementClient):
        self._client = client
        self._rows: list[tuple] | None = None
        self._pos = 0
        self.description = None
        self.rowcount = -1

    def execute(self, sql: str, parameters=None):
        if parameters:
            sql = _substitute(sql, parameters)
        try:
            columns, rows = self._client.execute(sql)
        except QueryError as e:
            raise DatabaseError(str(e)) from e
        self.description = [
            (c["name"], c.get("type"), None, None, None, None, None)
            for c in columns
        ]
        self._rows = [tuple(r) for r in rows]
        self._pos = 0
        self.rowcount = len(self._rows)
        return self

    def executemany(self, sql: str, seq_of_parameters):
        for p in seq_of_parameters:
            self.execute(sql, p)
        return self

    def fetchone(self):
        if self._rows is None:
            raise InterfaceError("no query has been executed")
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: int | None = None):
        n = self.arraysize if size is None else size
        out = []
        for _ in range(n):
            r = self.fetchone()
            if r is None:
                break
            out.append(r)
        return out

    def fetchall(self):
        if self._rows is None:
            raise InterfaceError("no query has been executed")
        out = self._rows[self._pos:]
        self._pos = len(self._rows)
        return out

    def __iter__(self):
        while True:
            r = self.fetchone()
            if r is None:
                return
            yield r

    def close(self):
        self._rows = None

    def setinputsizes(self, sizes):
        pass

    def setoutputsize(self, size, column=None):
        pass


def _substitute(sql: str, parameters) -> str:
    """qmark substitution with SQL-literal quoting (server side has no
    prepared statements yet, mirroring the JDBC driver's client-side
    fallback). '?' inside string literals is left alone."""
    params = list(parameters)
    out = []
    it = iter(params)
    used = 0
    in_string = False
    for ch in sql:
        if ch == "'":
            in_string = not in_string  # '' escapes toggle twice: fine
            out.append(ch)
        elif ch == "?" and not in_string:
            try:
                v = next(it)
            except StopIteration:
                raise ProgrammingError(
                    "not enough parameters for placeholders"
                ) from None
            used += 1
            out.append(_quote(v))
        else:
            out.append(ch)
    if used != len(params):
        raise ProgrammingError(
            f"{len(params)} parameters for {used} placeholders"
        )
    return "".join(out)


def _quote(v) -> str:
    import datetime
    import decimal

    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        import math

        if not math.isfinite(v):
            raise DataError(f"cannot bind non-finite float {v!r}")
        return repr(v)
    if isinstance(v, int):
        return repr(v)
    # typed literals: the engine has no varchar->decimal/date/timestamp
    # coercion, so quoted strings would fail analysis for these binds
    if isinstance(v, decimal.Decimal):
        if not v.is_finite():
            raise DataError(f"cannot bind non-finite decimal {v!r}")
        # plain notation: str() would emit 1E-8 for small values, which
        # the lexer tokenizes as a double literal
        return format(v, "f")
    if isinstance(v, datetime.datetime):
        if v.tzinfo is not None:
            raise DataError("cannot bind tz-aware datetime (no TZ type)")
        return f"TIMESTAMP '{v.isoformat(sep=' ')}'"
    if isinstance(v, datetime.date):
        return f"DATE '{v.isoformat()}'"
    if isinstance(v, (bytes, bytearray, memoryview)):
        raise DataError("cannot bind binary parameters (no VARBINARY type)")
    s = str(v).replace("'", "''")
    return f"'{s}'"
