"""Interactive SQL CLI.

The analog of the reference's terminal client
(client/trino-cli/.../Console.java:86): reads statements (terminated
by ';'), sends them through the REST protocol, renders aligned tables.
Run as:

    python -m trino_tpu.server.cli [--server URL] [--execute SQL]

Without --server, an embedded coordinator is started over the TPC-H
catalog (the dev loop the reference serves with TestingTrinoServer).
"""

from __future__ import annotations

import argparse
import sys

from trino_tpu.server.client import QueryError, StatementClient

__all__ = ["main", "render_table"]


def render_table(columns: list[dict], rows: list[list]) -> str:
    if not columns:
        return "(no columns)"
    headers = [c["name"] for c in columns]
    cells = [[_fmt(v) for v in row] for row in rows]
    widths = [
        max(len(h), *(len(r[i]) for r in cells)) if cells else len(h)
        for i, h in enumerate(headers)
    ]
    sep = "-+-".join("-" * w for w in widths)
    out = [
        " | ".join(h.ljust(w) for h, w in zip(headers, widths)),
        sep,
    ]
    for r in cells:
        out.append(" | ".join(v.ljust(w) for v, w in zip(r, widths)))
    out.append(f"({len(rows)} row{'s' if len(rows) != 1 else ''})")
    return "\n".join(out)


def _fmt(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="trino-tpu")
    ap.add_argument("--server", help="coordinator URL (default: embedded)")
    ap.add_argument("--execute", "-e", help="run one statement and exit")
    ap.add_argument(
        "--schema", default="tiny", help="TPC-H schema for embedded mode"
    )
    args = ap.parse_args(argv)

    coordinator = None
    if args.server:
        server = args.server
    else:
        from trino_tpu.engine import QueryRunner
        from trino_tpu.server.coordinator import Coordinator

        coordinator = Coordinator(QueryRunner.tpch(args.schema)).start()
        server = coordinator.uri
        print(f"embedded coordinator at {server}", file=sys.stderr)
    client = StatementClient(server)

    def run_one(sql: str) -> int:
        sql = sql.strip().rstrip(";").strip()
        if not sql:
            return 0
        try:
            columns, rows = client.execute(sql)
        except QueryError as e:
            print(f"Query failed: {e}", file=sys.stderr)
            return 1
        print(render_table(columns, rows))
        return 0

    try:
        if args.execute:
            return run_one(args.execute)
        print("trino-tpu> ", end="", flush=True)
        buf = ""
        quitting = False
        for line in sys.stdin:
            buf += line
            while ";" in buf:
                stmt, buf = buf.split(";", 1)
                if stmt.strip().lower() in ("quit", "exit"):
                    quitting = True
                    break
                run_one(stmt)
            if quitting:
                break
            prompt = "trino-tpu> " if not buf.strip() else "        -> "
            print(prompt, end="", flush=True)
        if not quitting and buf.strip():
            run_one(buf)  # final statement without a trailing ';'
        return 0
    finally:
        if coordinator is not None:
            coordinator.stop()


if __name__ == "__main__":
    sys.exit(main())
