"""Fleet execution: stage-wave scheduling across N worker processes
with durable spooled stage outputs.

The analog of the reference's fault-tolerant query scheduler
(MAIN/execution/scheduler/faulttolerant/EventDrivenFaultTolerantQueryScheduler.java:200):
the coordinator plans SQL locally, cuts the plan into stages
(plan.fragment), and runs the stages as batch-synchronous waves.
Every task's output is committed to the spooled exchange (exec.spool)
before the next stage starts, so:

- inter-stage data crosses worker processes through durable
  hash-partitioned files (the DCN/FTE exchange tier, SURVEY.md §5.8) —
  never through worker memory;
- a task failure (or a kill -9'd worker) retries JUST that task on a
  surviving worker, reading identical spooled inputs — the query
  completes with oracle-exact results (TASK retry policy,
  MAIN/execution/QueryManagerConfig.java retry-policy);
- workers that vanish are excluded from further placement (the
  HeartbeatFailureDetector analog collapsed into RPC-failure
  detection, MAIN/failuredetector/HeartbeatFailureDetector.java:76).

Tasks per stage: a stage with aligned (hash) inputs runs one task per
partition; a stage scanning a table splits it into row ranges (one
task per split, SPI/connector/ConnectorSplit.java analog); everything
else runs as one task.
"""

from __future__ import annotations

import json
import os
import time
import urllib.request
import uuid
from collections import deque
from dataclasses import dataclass

from trino_tpu.engine import QueryResult, QueryRunner, _has_order
from trino_tpu.exec import spool
from trino_tpu.metadata import Metadata, Session
from trino_tpu.plan import nodes as P
from trino_tpu.plan.fragment import Stage, fragment_plan
from trino_tpu.plan.serde import plan_to_json
from trino_tpu.server.remote import _FakeMesh

__all__ = ["FleetRunner", "FleetWorker"]


@dataclass
class FleetWorker:
    uri: str
    alive: bool = True


@dataclass
class _TaskSpec:
    task_id: str
    plan_json: dict
    partition: int | None
    fail_first: bool = False


class FleetRunner:
    """QueryRunner-compatible facade scheduling stage waves over a
    fleet of worker processes."""

    def __init__(
        self,
        worker_uris: list[str],
        metadata: Metadata,
        session: Session,
        spool_root: str,
        n_partitions: int = 4,
        poll_s: float = 0.02,
        timeout_s: float = 600.0,
        max_attempts: int = 3,
        stage_hook=None,
        keep_spool: bool = False,
    ):
        self.workers = [FleetWorker(u.rstrip("/")) for u in worker_uris]
        self.metadata = metadata
        self.session = session
        self.spool_root = spool_root
        self.n_partitions = n_partitions
        self.poll_s = poll_s
        self.timeout_s = timeout_s
        self.max_attempts = max_attempts
        #: test hook called after each stage completes (stage_id) —
        #: deterministic point to kill a worker mid-query
        self.stage_hook = stage_hook
        self.keep_spool = keep_spool
        #: task ids to fail on their first attempt (FailureInjector
        #: analog, keyed "stage:task_index")
        self.inject_failures: set[str] = set()
        #: test hook called after each successful task submission
        #: (stage_id, task_id, worker) — deterministic point to crash
        #: the worker a task just landed on
        self.post_hook = None
        self._planner = QueryRunner(metadata, session)
        self._planner.mesh = _FakeMesh(max(n_partitions, 2))

    # ---- query entry -----------------------------------------------------

    def execute(self, sql: str) -> QueryResult:
        plan = self._planner.plan_sql(sql)
        stages = fragment_plan(plan)
        query_id = uuid.uuid4().hex[:12]
        qroot = os.path.join(self.spool_root, query_id)
        os.makedirs(qroot, exist_ok=True)
        tasks_by_stage: dict[str, list[str]] = {}
        try:
            for stage in stages:
                specs = self._make_tasks(stage)
                self._run_wave(stage, specs, qroot, tasks_by_stage)
                tasks_by_stage[stage.stage_id] = [s.task_id for s in specs]
                if self.stage_hook is not None:
                    self.stage_hook(stage.stage_id)
            root = stages[-1]
            payload = spool.read_partition(
                qroot, root.stage_id, tasks_by_stage[root.stage_id], None
            )
            page = spool.host_to_page(payload)
            rows = page.to_pylist()
            return QueryResult(
                names=list(page.names), rows=rows,
                ordered=_has_order(plan), plan=plan,
            )
        finally:
            if not self.keep_spool:
                import shutil

                shutil.rmtree(qroot, ignore_errors=True)

    # ---- task construction -----------------------------------------------

    def _make_tasks(self, stage: Stage) -> list[_TaskSpec]:
        sid = stage.stage_id
        if stage.aligned:
            wire = plan_to_json(stage.root)
            return [
                _TaskSpec(
                    f"s{sid}p{p}", wire, p,
                    fail_first=f"{sid}:{p}" in self.inject_failures,
                )
                for p in range(self.n_partitions)
            ]
        scans = stage.scans()
        if len(scans) == 1 and scans[0].split is None:
            scan = scans[0]
            connector = self.metadata.connector(scan.catalog)
            n_live = max(2, sum(1 for w in self.workers if w.alive))
            splits = connector.splits(scan.schema, scan.table, n_live)
            specs = []
            for i, sp in enumerate(splits):
                bound = _bind_split(stage.root, scan, (sp.start, sp.count))
                specs.append(
                    _TaskSpec(
                        f"s{sid}t{i}", plan_to_json(bound), None,
                        fail_first=f"{sid}:{i}" in self.inject_failures,
                    )
                )
            return specs
        return [
            _TaskSpec(
                f"s{sid}t0", plan_to_json(stage.root), None,
                fail_first=f"{sid}:0" in self.inject_failures,
            )
        ]

    # ---- wave scheduling with retry --------------------------------------

    def _run_wave(
        self, stage: Stage, specs: list[_TaskSpec], qroot: str,
        tasks_by_stage: dict[str, list[str]],
    ) -> None:
        pending = deque(specs)
        inflight: dict[str, tuple[FleetWorker, _TaskSpec, int]] = {}
        attempts = {s.task_id: 0 for s in specs}
        done: set[str] = set()
        deadline = time.monotonic() + self.timeout_s
        while len(done) < len(specs):
            if time.monotonic() > deadline:
                raise TimeoutError(f"stage {stage.stage_id} timed out")
            live = [w for w in self.workers if w.alive]
            if not live:
                raise RuntimeError("no live workers remain")
            busy = {id(w) for (w, _, _) in inflight.values()}
            for w in live:
                if not pending:
                    break
                if id(w) in busy:
                    continue
                spec = pending.popleft()
                a = attempts[spec.task_id]
                try:
                    self._post_task(w, stage, spec, a, qroot, tasks_by_stage)
                    inflight[spec.task_id] = (w, spec, a)
                    busy.add(id(w))
                    if self.post_hook is not None:
                        self.post_hook(stage.stage_id, spec.task_id, w)
                except Exception:
                    w.alive = False
                    pending.appendleft(spec)
            for tid, (w, spec, a) in list(inflight.items()):
                try:
                    state = self._poll_task(w, tid, a)
                except Exception:
                    # the worker vanished mid-task (crash/kill -9):
                    # exclude it and reschedule from spooled inputs
                    w.alive = False
                    del inflight[tid]
                    self._bump_attempt(spec, attempts, "worker died")
                    pending.append(spec)
                    continue
                if state["state"] == "FINISHED":
                    done.add(tid)
                    del inflight[tid]
                elif state["state"] == "FAILED":
                    del inflight[tid]
                    self._bump_attempt(
                        spec, attempts, state.get("error", "task failed")
                    )
                    pending.append(spec)
            if inflight or not pending:
                time.sleep(self.poll_s)

    def _bump_attempt(self, spec: _TaskSpec, attempts: dict, error: str):
        attempts[spec.task_id] += 1
        if attempts[spec.task_id] >= self.max_attempts:
            raise RuntimeError(
                f"task {spec.task_id} failed after "
                f"{attempts[spec.task_id]} attempts: {error}"
            )

    # ---- worker RPC ------------------------------------------------------

    def _post_task(
        self, w: FleetWorker, stage: Stage, spec: _TaskSpec, attempt: int,
        qroot: str, tasks_by_stage: dict[str, list[str]],
    ) -> None:
        req = {
            "task_id": spec.task_id,
            "attempt": attempt,
            "plan": spec.plan_json,
            "partition": spec.partition,
            "sources": [
                {
                    "source_id": i.source_id,
                    "stage_id": i.stage_id,
                    "mode": i.mode,
                    "task_ids": tasks_by_stage[i.stage_id],
                }
                for i in stage.inputs
            ],
            "output": {
                "stage_id": stage.stage_id,
                "partitioning": stage.partitioning,
                "hash_symbols": stage.hash_symbols,
                "n_partitions": self.n_partitions,
            },
            "spool": qroot,
            "session": dict(self.session.properties),
            "fail": bool(spec.fail_first and attempt == 0),
        }
        body = json.dumps(req).encode()
        r = urllib.request.Request(
            f"{w.uri}/v1/stagetask", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(r, timeout=30) as resp:
            json.loads(resp.read())

    def _poll_task(self, w: FleetWorker, task_id: str, attempt: int) -> dict:
        with urllib.request.urlopen(
            f"{w.uri}/v1/stagetask/{task_id}.{attempt}", timeout=30
        ) as resp:
            return json.loads(resp.read())


def _bind_split(
    root: P.PlanNode, scan: P.TableScan, split: tuple[int, int]
) -> P.PlanNode:
    """Rebind the fragment's scan leaf to one split."""
    from dataclasses import replace as dc_replace

    from trino_tpu.plan.optimizer import _replace_sources

    def walk(n: P.PlanNode) -> P.PlanNode:
        if n is scan:
            return dc_replace(n, split=split)
        srcs = n.sources
        if not srcs:
            return n
        return _replace_sources(n, [walk(s) for s in srcs])

    return walk(root)
