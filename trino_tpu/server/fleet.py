"""Fleet execution: stage-wave scheduling across N worker processes
with durable spooled stage outputs.

The analog of the reference's fault-tolerant query scheduler
(MAIN/execution/scheduler/faulttolerant/EventDrivenFaultTolerantQueryScheduler.java:200):
the coordinator plans SQL locally, cuts the plan into stages
(plan.fragment), and runs the stages as batch-synchronous waves.
Every task's output is committed to the spooled exchange (exec.spool)
before the next stage starts, so:

- inter-stage data crosses worker processes through durable
  hash-partitioned files (the DCN/FTE exchange tier, SURVEY.md §5.8) —
  never through worker memory;
- a task failure (or a kill -9'd worker) retries JUST that task on a
  surviving worker, reading identical spooled inputs — the query
  completes with oracle-exact results (TASK retry policy,
  MAIN/execution/QueryManagerConfig.java retry-policy);
- workers that vanish are excluded from further placement (the
  HeartbeatFailureDetector analog collapsed into RPC-failure
  detection, MAIN/failuredetector/HeartbeatFailureDetector.java:76).

Tasks per stage: a stage with aligned (hash) inputs runs one task per
partition; a stage scanning a table splits it into row ranges (one
task per split, SPI/connector/ConnectorSplit.java analog); everything
else runs as one task.
"""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request
import uuid
from collections import deque
from dataclasses import dataclass

from trino_tpu.engine import QueryResult, QueryRunner, _has_order
from trino_tpu.exec import spool
from trino_tpu.metadata import Metadata, Session
from trino_tpu.plan import nodes as P
from trino_tpu.plan.fragment import Stage, fragment_plan
from trino_tpu.plan.serde import plan_to_json

__all__ = ["FleetRunner", "FleetWorker"]


class _FleetParallelism:
    """Duck-typed mesh stand-in for plan_stmt: the fleet's TOTAL
    parallelism (spool partitions x per-worker device count, the
    latter discovered from each worker's /v1/info). Distribution
    planning sees the real shard count a key space divides into —
    capacity estimates and broadcast thresholds match what actually
    runs (VERDICT r4: the fixed _FakeMesh ignored worker meshes)."""

    def __init__(self, n: int):
        self.devices = _N(n)


class _N:
    def __init__(self, n: int):
        self.size = n


@dataclass
class FleetWorker:
    uri: str
    alive: bool = True
    #: DRAINING per /v1/info or a 409 task rejection: no new tasks,
    #: in-flight ones still polled to completion
    draining: bool = False
    #: consecutive poll timeouts (hung-worker detection: a SIGSTOPped
    #: process holds connections open without answering — N short
    #: timeouts in a row declare it dead, vs one long RPC timeout)
    fails: int = 0


@dataclass
class _TaskSpec:
    task_id: str
    plan_json: dict
    partition: int | None
    fail_first: bool = False


class FleetRunner:
    """QueryRunner-compatible facade scheduling stage waves over a
    fleet of worker processes."""

    def __init__(
        self,
        worker_uris: list[str],
        metadata: Metadata,
        session: Session,
        spool_root: str,
        n_partitions: int = 4,
        poll_s: float = 0.02,
        timeout_s: float = 600.0,
        max_attempts: int = 3,
        rpc_timeout_s: float = 15.0,
        max_poll_fails: int = 4,
        stage_hook=None,
        keep_spool: bool = False,
    ):
        self.workers = [FleetWorker(u.rstrip("/")) for u in worker_uris]
        self.metadata = metadata
        self.session = session
        self.spool_root = spool_root
        self.n_partitions = n_partitions
        self.poll_s = poll_s
        self.timeout_s = timeout_s
        #: constructor default; a per-query session override
        #: (retry_max_attempts) applies for that execute() only
        self._default_max_attempts = max_attempts
        self.max_attempts = max_attempts
        #: per-RPC timeout: hung-worker detection latency is
        #: rpc_timeout_s * max_poll_fails (HeartbeatFailureDetector
        #: analog: liveness from RPC health, MAIN/failuredetector/
        #: HeartbeatFailureDetector.java:76). The defaults tolerate
        #: multi-second GIL stalls while a worker traces/compiles a
        #: stage program — a worker slow to ANSWER is not dead; only
        #: max_poll_fails consecutive timeouts (or a refused
        #: connection) declare it so
        self.rpc_timeout_s = rpc_timeout_s
        self.max_poll_fails = max_poll_fails
        #: test hook called after each stage completes (stage_id) —
        #: deterministic point to kill a worker mid-query
        self.stage_hook = stage_hook
        self.keep_spool = keep_spool
        #: task ids to fail on their first attempt (FailureInjector
        #: analog, keyed "stage:task_index")
        self.inject_failures: set[str] = set()
        #: test hook called after each successful task submission
        #: (stage_id, task_id, worker) — deterministic point to crash
        #: the worker a task just landed on
        self.post_hook = None
        self._planner = QueryRunner(metadata, session)
        #: per-worker device counts from /v1/info (1 when unreachable
        #: or mesh-less); the planner's shard count is the fleet total
        self.worker_devices = {
            w.uri: self._probe_devices(w.uri) for w in self.workers
        }
        per_worker = max(self.worker_devices.values(), default=1)
        self._planner.mesh = _FleetParallelism(
            max(n_partitions, 2) * per_worker
        )

    @staticmethod
    def _probe_devices(uri: str) -> int:
        try:
            with urllib.request.urlopen(f"{uri}/v1/info", timeout=5) as r:
                return max(int(json.loads(r.read()).get("devices", 1)), 1)
        except Exception:
            return 1

    # ---- query entry -----------------------------------------------------

    def execute(self, sql: str) -> QueryResult:
        self.max_attempts = int(
            self.session.properties.get(
                "retry_max_attempts", self._default_max_attempts
            )
        )
        plan = self._planner.plan_sql(sql)
        stages = fragment_plan(plan)
        query_id = uuid.uuid4().hex[:12]
        qroot = os.path.join(self.spool_root, query_id)
        os.makedirs(qroot, exist_ok=True)
        tasks_by_stage: dict[str, list[str]] = {}
        try:
            self._run_dag(stages, qroot, tasks_by_stage)
            root = stages[-1]
            payload = spool.read_partition(
                qroot, root.stage_id, tasks_by_stage[root.stage_id], None
            )
            page = spool.host_to_page(payload)
            rows = page.to_pylist()
            return QueryResult(
                names=list(page.names), rows=rows,
                ordered=_has_order(plan), plan=plan,
            )
        finally:
            if not self.keep_spool:
                import shutil

                shutil.rmtree(qroot, ignore_errors=True)

    # ---- task construction -----------------------------------------------

    def _make_tasks(self, stage: Stage) -> list[_TaskSpec]:
        sid = stage.stage_id
        if stage.aligned:
            wire = plan_to_json(stage.root)
            return [
                _TaskSpec(
                    f"s{sid}p{p}", wire, p,
                    fail_first=f"{sid}:{p}" in self.inject_failures,
                )
                for p in range(self.n_partitions)
            ]
        scans = stage.scans()
        if len(scans) == 1 and scans[0].split is None:
            scan = scans[0]
            connector = self.metadata.connector(scan.catalog)
            n_live = max(2, sum(1 for w in self.workers if w.alive))
            splits = connector.splits(scan.schema, scan.table, n_live)
            specs = []
            for i, sp in enumerate(splits):
                bound = _bind_split(stage.root, scan, (sp.start, sp.count))
                specs.append(
                    _TaskSpec(
                        f"s{sid}t{i}", plan_to_json(bound), None,
                        fail_first=f"{sid}:{i}" in self.inject_failures,
                    )
                )
            return specs
        return [
            _TaskSpec(
                f"s{sid}t0", plan_to_json(stage.root), None,
                fail_first=f"{sid}:0" in self.inject_failures,
            )
        ]

    # ---- overlapping stage-DAG scheduling with retry ---------------------

    def _run_dag(
        self, stages: list[Stage], qroot: str,
        tasks_by_stage: dict[str, list[str]],
    ) -> None:
        """Schedule ALL stages through one event loop: a stage becomes
        READY the moment every input stage has committed (spool commits
        are per-task and atomic), so independent subtrees — the two
        scan stages under a partitioned join, the branches of a UNION —
        interleave across the worker pool instead of running as strict
        sequential waves (the PipelinedQueryScheduler direction,
        MAIN/execution/scheduler/PipelinedQueryScheduler.java:156,
        within the FTE stage-commit durability model)."""
        by_id = {s.stage_id: s for s in stages}
        specs_of: dict[str, list[_TaskSpec]] = {}
        done_of: dict[str, set] = {s.stage_id: set() for s in stages}
        complete: set[str] = set()
        started: set[str] = set()
        #: per-stage task queues, dispatched round-robin so independent
        #: ready stages make progress TOGETHER (a FIFO would fill the
        #: pool with the first stage's tasks and serialize subtrees)
        queues: dict[str, deque] = {}
        rr: deque[str] = deque()  # round-robin order over queues
        inflight: dict[str, tuple[FleetWorker, Stage, _TaskSpec, int]] = {}
        attempts: dict[str, int] = {}
        deadline = time.monotonic() + self.timeout_s

        def push(stage: Stage, spec: _TaskSpec) -> None:
            sid = stage.stage_id
            if sid not in queues:
                queues[sid] = deque()
                rr.append(sid)
            queues[sid].append(spec)

        def n_pending() -> int:
            return sum(len(q) for q in queues.values())

        def take_next():
            """Next (stage, spec) round-robin across non-empty queues."""
            for _ in range(len(rr)):
                sid = rr[0]
                rr.rotate(-1)
                q = queues.get(sid)
                if q:
                    return by_id[sid], q.popleft()
            return None

        def ready(stage: Stage) -> bool:
            return all(i.stage_id in complete for i in stage.inputs)

        while len(complete) < len(stages):
            if time.monotonic() > deadline:
                raise TimeoutError("query stages timed out")
            # admit newly-ready stages (task construction sees current
            # worker liveness, so it happens at admission, not upfront)
            for stage in stages:
                if stage.stage_id in started or not ready(stage):
                    continue
                specs = self._make_tasks(stage)
                specs_of[stage.stage_id] = specs
                for spec in specs:
                    attempts[spec.task_id] = 0
                    push(stage, spec)
                started.add(stage.stage_id)
            live = [w for w in self.workers if w.alive]
            if not live:
                raise RuntimeError("no live workers remain")
            postable = [w for w in live if not w.draining]
            if n_pending() and not postable and not inflight:
                raise RuntimeError(
                    "all remaining workers are draining; tasks cannot "
                    "be placed"
                )
            busy = {id(w) for (w, _, _, _) in inflight.values()}
            for _ in range(n_pending()):
                # NOTE: no busy-count early-out — `busy` includes
                # draining/hung workers holding in-flight tasks, which
                # are not in `postable`; counting them would idle free
                # workers. The `w is None` probe below is the real
                # "no free worker" exit.
                nxt = take_next()
                if nxt is None:
                    break
                stage, spec = nxt
                w = next(
                    (w for w in postable if id(w) not in busy), None
                )
                if w is None:
                    queues[stage.stage_id].appendleft(spec)
                    break
                a = attempts[spec.task_id]
                try:
                    self._post_task(w, stage, spec, a, qroot, tasks_by_stage)
                    inflight[spec.task_id] = (w, stage, spec, a)
                    busy.add(id(w))
                    if self.post_hook is not None:
                        self.post_hook(stage.stage_id, spec.task_id, w)
                except urllib.error.HTTPError as e:
                    if e.code == 409:
                        # 409 = draining: alive, just not accepting —
                        # reschedule elsewhere, keep polling its tasks
                        w.draining = True
                        postable = [x for x in postable if x is not w]
                    else:
                        w.alive = False
                        postable = [x for x in postable if x is not w]
                    queues[stage.stage_id].appendleft(spec)
                except Exception:
                    w.alive = False
                    postable = [x for x in postable if x is not w]
                    queues[stage.stage_id].appendleft(spec)
            for tid, (w, stage, spec, a) in list(inflight.items()):
                try:
                    state = self._poll_task(w, tid, a)
                    w.fails = 0
                except Exception as e:
                    # crash/kill -9 refuses the connection: dead now.
                    # A hung-but-alive worker (SIGSTOP) keeps the
                    # socket open and times out: N consecutive short
                    # timeouts declare it dead — detection latency
                    # rpc_timeout_s * max_poll_fails, not one long RPC
                    # timeout (VERDICT r4 missing #8)
                    refused = isinstance(
                        getattr(e, "reason", None), ConnectionRefusedError
                    ) or isinstance(e, ConnectionRefusedError)
                    w.fails += 1
                    if not (refused or w.fails >= self.max_poll_fails):
                        continue  # transient: re-poll next loop
                    w.alive = False
                    del inflight[tid]
                    self._bump_attempt(spec, attempts, "worker died")
                    push(stage, spec)
                    continue
                if state["state"] == "FINISHED":
                    sid = stage.stage_id
                    done_of[sid].add(tid)
                    del inflight[tid]
                    if len(done_of[sid]) == len(specs_of[sid]):
                        tasks_by_stage[sid] = [
                            s.task_id for s in specs_of[sid]
                        ]
                        complete.add(sid)
                        if self.stage_hook is not None:
                            self.stage_hook(sid)
                elif state["state"] == "FAILED":
                    del inflight[tid]
                    self._bump_attempt(
                        spec, attempts, state.get("error", "task failed")
                    )
                    push(stage, spec)
            if inflight or not n_pending():
                time.sleep(self.poll_s)
        assert set(tasks_by_stage) == set(by_id)

    def _bump_attempt(self, spec: _TaskSpec, attempts: dict, error: str):
        attempts[spec.task_id] += 1
        if attempts[spec.task_id] >= self.max_attempts:
            raise RuntimeError(
                f"task {spec.task_id} failed after "
                f"{attempts[spec.task_id]} attempts: {error}"
            )

    # ---- worker RPC ------------------------------------------------------

    def _post_task(
        self, w: FleetWorker, stage: Stage, spec: _TaskSpec, attempt: int,
        qroot: str, tasks_by_stage: dict[str, list[str]],
    ) -> None:
        req = {
            "task_id": spec.task_id,
            "attempt": attempt,
            "plan": spec.plan_json,
            "partition": spec.partition,
            "sources": [
                {
                    "source_id": i.source_id,
                    "stage_id": i.stage_id,
                    "mode": i.mode,
                    "hash_symbols": list(i.hash_symbols),
                    "task_ids": tasks_by_stage[i.stage_id],
                }
                for i in stage.inputs
            ],
            "output": {
                "stage_id": stage.stage_id,
                "partitioning": stage.partitioning,
                "hash_symbols": stage.hash_symbols,
                "n_partitions": self.n_partitions,
            },
            "spool": qroot,
            "session": dict(self.session.properties),
            "fail": bool(spec.fail_first and attempt == 0),
        }
        body = json.dumps(req).encode()
        r = urllib.request.Request(
            f"{w.uri}/v1/stagetask", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(
            r, timeout=self.rpc_timeout_s
        ) as resp:
            json.loads(resp.read())

    def _poll_task(self, w: FleetWorker, task_id: str, attempt: int) -> dict:
        with urllib.request.urlopen(
            f"{w.uri}/v1/stagetask/{task_id}.{attempt}",
            timeout=self.rpc_timeout_s,
        ) as resp:
            return json.loads(resp.read())


def _bind_split(
    root: P.PlanNode, scan: P.TableScan, split: tuple[int, int]
) -> P.PlanNode:
    """Rebind the fragment's scan leaf to one split."""
    from dataclasses import replace as dc_replace

    from trino_tpu.plan.optimizer import _replace_sources

    def walk(n: P.PlanNode) -> P.PlanNode:
        if n is scan:
            return dc_replace(n, split=split)
        srcs = n.sources
        if not srcs:
            return n
        return _replace_sources(n, [walk(s) for s in srcs])

    return walk(root)
